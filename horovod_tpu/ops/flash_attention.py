"""Pallas flash attention — the fused single-chip attention hot path.

The transformer family's attention math (`full_attention`) leaves XLA to
materialize the (T, T) logits in HBM.  This kernel computes the same
causal softmax-attention with the flash schedule instead: Q blocks stay
resident in VMEM while K/V blocks stream through, the online-softmax
accumulators (running max / sum / output, all f32) never leave VMEM, and
the MXU sees back-to-back (block_q x d) @ (d x block_k) matmuls.  HBM
traffic drops from O(T^2) to O(T·d).

Layout: grid ``(batch*heads, T/block_q, T/block_k)`` with the KV axis
innermost ("arbitrary" semantics — accumulators persist across it);
causal Q/KV block pairs that are entirely masked are skipped with
``pl.when``, halving the work like the zigzag ring layout does across
chips.

Backward: ``jax.custom_vjp`` saving (o, logsumexp); gradients use the
standard flash-backward identities (dS = P * (dP - rowsum(dO*o))) as two
Pallas kernels with the same VMEM-resident blockwise schedule as the
forward — one accumulating dk/dv per KV block while Q blocks stream, one
accumulating dq per Q block while KV blocks stream (the FlashAttention-2
split).  A chunked XLA backward remains as the ``bwd_impl="xla"``
fallback.

Composition: this is the *single-chip* block; for sequences sharded
across chips use :mod:`horovod_tpu.parallel.ring_attention`, which
streams K/V between chips with the same online-softmax math.

``interpret=True`` runs the kernel on CPU for tests; on TPU the shapes
must tile ((block sizes multiples of 128 ideally), else the caller should
fall back to ``full_attention``).
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Shared with the oracle/ring implementations so masking stays numerically
# identical across all attention paths.
from horovod_tpu.parallel.ring_attention import _NEG_BIG, full_attention


def _flash_vmem_mb() -> int:
    """Per-kernel VMEM budget (MB) for the head-group blocked backward
    pair — the single parse point for ``HOROVOD_TPU_FLASH_VMEM_MB`` so
    the auto-select guard and the applied budget cannot drift apart.
    Default 32 (measured sufficient for g2 at 1024² blocks, D=128);
    0 restores Mosaic's compiler default; a malformed value warns and
    falls back rather than raising mid-backward."""
    raw = os.environ.get("HOROVOD_TPU_FLASH_VMEM_MB")
    if raw is None:
        # The raised default only applies where the hardware can back it
        # (v2/v3 have 16 MB of physical VMEM per core): an explicit
        # HOROVOD_TPU_FLASH_BWD_GROUP opt-in at small blocks compiled
        # fine under Mosaic's default budget there, and must keep doing
        # so without the user also discovering the VMEM knob.  Computed
        # only on this branch — _vmem_headroom_ok touches the device
        # list, which an explicit valid value never needs.
        return 32 if _vmem_headroom_ok() else 0
    try:
        val = int(raw)
        if val < 0:
            raise ValueError
        return val
    except ValueError:
        import warnings
        default = 32 if _vmem_headroom_ok() else 0
        warnings.warn(
            f"HOROVOD_TPU_FLASH_VMEM_MB={raw!r} is not a non-negative "
            f"integer; using the default {default}",
            RuntimeWarning, stacklevel=2)
        return default


# The fully-unrolled forward's Mosaic stack crosses the default scoped-VMEM
# budget past T=2048 (measured 44.4 MB at T=4096) — it needs at least this
# much or it stands down to the unrolled-KV form.
_FWD_MIN_VMEM_MB = 64


def _flash_fwd_vmem_mb() -> int:
    """VMEM budget (MB) for the fully-unrolled forward at 2048<T.

    ``HOROVOD_TPU_FLASH_FWD_VMEM_MB`` rules when set (the forward's own
    knob, honored as given).  Otherwise an explicitly set shared
    ``HOROVOD_TPU_FLASH_VMEM_MB`` rules — but its documented default
    (32) targets the grouped backward, so pinning that value would stand
    the forward down as a side effect the user never asked for: warn
    when that happens (an explicit 0 = compiler default stays silent —
    that is a deliberate opt-out).  With neither set, auto-grant 64
    where the hardware backs it."""
    raw = os.environ.get("HOROVOD_TPU_FLASH_FWD_VMEM_MB")
    if raw is not None:
        try:
            val = int(raw)
            if val < 0:
                raise ValueError
            return val
        except ValueError:
            import warnings
            default = _FWD_MIN_VMEM_MB if _vmem_headroom_ok() else 0
            warnings.warn(
                f"HOROVOD_TPU_FLASH_FWD_VMEM_MB={raw!r} is not a "
                f"non-negative integer; using the default {default}",
                RuntimeWarning, stacklevel=3)
            return default
    if os.environ.get("HOROVOD_TPU_FLASH_VMEM_MB") is None:
        return _FWD_MIN_VMEM_MB if _vmem_headroom_ok() else 0
    val = _flash_vmem_mb()
    if 0 < val < _FWD_MIN_VMEM_MB:
        import warnings
        warnings.warn(
            f"HOROVOD_TPU_FLASH_VMEM_MB={val} is below the "
            f"{_FWD_MIN_VMEM_MB} MB the fully-unrolled forward needs "
            "past T=2048, so that form stands down (the unrolled-KV "
            "form takes over). Set HOROVOD_TPU_FLASH_FWD_VMEM_MB to "
            "budget the forward separately from the grouped backward.",
            RuntimeWarning, stacklevel=3)
    return val


# TPU generations with only 16 MB of physical VMEM per core — the raised
# grouped-kernel budget cannot be backed there, so auto-selection stands
# down (explicit HOROVOD_TPU_FLASH_BWD_GROUP still applies as given).
_SMALL_VMEM_DEVICE_KINDS = ("v2", "v3")


def _vmem_headroom_ok() -> bool:
    try:
        d = jax.local_devices()[0]
    except Exception:   # noqa: BLE001 — uninitialized backend
        return True
    if d.platform != "tpu":
        return True   # CPU/interpret: the limit is not enforced
    try:
        kind = (d.device_kind or "").lower()
    except Exception:   # noqa: BLE001 — runtime refused the query
        kind = ""
    if not kind:
        # A TPU whose generation cannot be read could be a v2/v3 with
        # 16 MB of physical VMEM: fail closed — a stood-down raised
        # budget costs a slower kernel form, an over-request fails the
        # whole compile.
        return False
    return not any(g in kind for g in _SMALL_VMEM_DEVICE_KINDS)


def _struct(shape, dtype, *like):
    """ShapeDtypeStruct for a pallas output, inheriting the union of the
    inputs' varying-manual-axes: under ``shard_map(check_vma=True)`` the
    kernel outputs vary over exactly the axes the inputs do, and jax
    requires that declared explicitly."""
    vma = frozenset()
    for l in like:
        vma |= getattr(jax.typeof(l), "vma", None) or frozenset()
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _block_mask(qi, kj, block_q, block_k, causal, seq_len):
    """(BQ, BK) validity mask for this block pair, or None when every
    position is valid.  ``seq_len``: real sequence length when the array
    is zero-padded to a tileable T (positions >= seq_len are masked on
    both the row and column side, keeping padded-row softmax grads from
    producing inf*0 NaNs in the backward)."""
    if not causal and seq_len is None:
        return None
    rows = qi * block_q + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    cols = kj * block_k + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    ok = None
    if causal:
        ok = cols <= rows
    if seq_len is not None:
        lim = jnp.logical_and(rows < seq_len, cols < seq_len)
        ok = lim if ok is None else jnp.logical_and(ok, lim)
    return ok


def _interior(qi, kj, block_q, block_k, causal, seq_len):
    """True when every position of this block pair is valid, so the
    masked code path (iota + two selects per block) can be skipped.
    Returns the literal ``True`` when no masking can ever apply."""
    ok = True
    if causal:
        # Fully visible iff the last key column <= the first query row.
        ok = jnp.logical_and(ok, (kj + 1) * block_k - 1 <= qi * block_q)
    if seq_len is not None:
        ok = jnp.logical_and(
            ok, jnp.logical_and((qi + 1) * block_q <= seq_len,
                                (kj + 1) * block_k <= seq_len))
    return ok


def _masked_dispatch(compute, live, qi, kj, block_q, block_k, causal,
                     seq_len):
    """Run ``compute(masked=...)`` under ``live``: an unmasked interior
    fast path plus a masked boundary path (mask elision — on a causal
    grid about half the live blocks are interior and skip all iota/where
    VPU work).  When no masking can ever apply, only the unmasked body is
    emitted (no dead branch in the compiled kernel)."""
    interior = _interior(qi, kj, block_q, block_k, causal, seq_len)
    if interior is True:
        pl.when(live)(functools.partial(compute, masked=False))
        return
    pl.when(jnp.logical_and(live, interior))(
        functools.partial(compute, masked=False))
    pl.when(jnp.logical_and(live, jnp.logical_not(interior)))(
        functools.partial(compute, masked=True))


def _static_dead(qi: int, kj: int, block: int, causal, seq_len) -> bool:
    """Trace-time dead test for the fully-unrolled kernels (python-int
    block pair): causal-future pairs and pairs entirely inside the
    padding tail emit no code at all."""
    if causal and kj * block > (qi + 1) * block - 1:
        return True
    return seq_len is not None and (kj * block >= seq_len
                                    or qi * block >= seq_len)


def _static_interior(qi: int, kj: int, block: int, causal,
                     seq_len) -> bool:
    """Trace-time interior test (python-int block pair): True when no
    element of the pair can be masked, so the where/iota path is
    skipped statically."""
    return ((not causal or (kj + 1) * block - 1 <= qi * block)
            and (seq_len is None
                 or (max(qi, kj) + 1) * block <= seq_len))


def _live_block(qi, kj, block_q, block_k, causal, seq_len):
    """Whether this block pair contributes at all: causal-future KV
    blocks and block rows/columns entirely inside the padding tail are
    skipped outright."""
    q_last = (qi + 1) * block_q - 1
    k_first = kj * block_k
    live = jnp.logical_or(not causal, k_first <= q_last)
    if seq_len is not None:
        live = jnp.logical_and(live, k_first < seq_len)
        live = jnp.logical_and(live, qi * block_q < seq_len)
    return live


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, causal, block_q, block_k,
                seq_len, axes=(1, 2)):
    qi = pl.program_id(axes[0])
    kj = pl.program_id(axes[1])
    nk = pl.num_programs(axes[1])
    # Packed layout: refs are 4-D blocks (1, 1, block, w) with the head
    # as its own grid axis; legacy merged layout is 3-D (1, block, w).
    row8 = (0, 0) if lse_ref.ndim == 4 else (0,)

    @pl.when(kj == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_BIG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def _compute(masked: bool):
        # Matmuls consume the native (bf16) element type so the MXU runs
        # at full rate; accumulation is f32 via preferred_element_type.
        q = q_ref[0]                                  # (BQ, D)
        k = k_ref[0]                                  # (BK, D)
        v = v_ref[0]                                  # (BK, D)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (BQ, BK)
        ok = (_block_mask(qi, kj, block_q, block_k, causal, seq_len)
              if masked else None)
        if ok is not None:
            s = jnp.where(ok, s, _NEG_BIG)
        m_prev = m_scr[...]                            # (BQ, 128)
        block_max = jnp.max(s, axis=1, keepdims=True)  # (BQ, 1)
        m_new = jnp.maximum(m_prev, jnp.broadcast_to(block_max,
                                                     m_prev.shape))
        alpha = jnp.exp(m_prev[:, :1] - m_new[:, :1])  # (BQ, 1)
        p = jnp.exp(s - m_new[:, :1])                  # (BQ, BK)
        if ok is not None:
            p = jnp.where(ok, p, 0.0)
        l_new = l_scr[...] * alpha + jnp.broadcast_to(
            jnp.sum(p, axis=1, keepdims=True), l_scr.shape)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = m_new
        l_scr[...] = l_new

    live = _live_block(qi, kj, block_q, block_k, causal, seq_len)
    _masked_dispatch(_compute, live, qi, kj, block_q, block_k, causal,
                     seq_len)

    @pl.when(kj == nk - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0] = (acc_scr[...] / l).astype(o_ref.dtype)
        # lse laid out (BQ, 8) — the minimal last-dim tile the TPU block
        # constraints allow for this narrow per-row scalar.
        lse_ref[row8] = jnp.broadcast_to(m_scr[:, :1] + jnp.log(l),
                                         (block_q, 8))


def _fwd(q, k, v, *, scale, causal, block_q, block_k, interpret,
         seq_len=None):
    BH, T, D = q.shape
    nq = T // block_q
    nk = T // block_k
    grid = (BH, nq, nk)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k,
                               seq_len=seq_len)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 8), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            _struct((BH, T, D), q.dtype, q, k, v),
            _struct((BH, T, 8), jnp.float32, q, k, v),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)
    return out, lse[..., 0]


def _fwd_kernel_unrollkv(q_ref, k_ref, v_ref, o_ref, lse_ref,
                         m_scr, l_scr, acc_scr, *, scale, causal,
                         block_q, block_k, seq_len, nk):
    """Forward with the WHOLE K/V row resident in VMEM and the KV loop
    unrolled inside one grid step (grid is (B, H, nq)).  The online
    softmax makes each KV step's accumulator update depend on the last,
    but the s = q k^T matmul of step j+1 depends only on the (invariant)
    q and k tiles — unrolling exposes that to Mosaic's scheduler, which
    overlaps step j's VPU softmax with step j+1's MXU matmul.  The
    grid-per-KV-block variant cannot (its per-step bodies serialize) and
    measured ~51% MXU on v5e; this form measured ~70%+
    (docs/benchmarks.md).  K/V are also fetched once per (b, h) instead
    of once per Q block."""
    qi = pl.program_id(2)
    m_scr[...] = jnp.full_like(m_scr, _NEG_BIG)
    l_scr[...] = jnp.zeros_like(l_scr)
    acc_scr[...] = jnp.zeros_like(acc_scr)

    def compute_for(kj):
        def _compute(masked: bool):
            q = q_ref[0]                                   # (BQ, D)
            k = k_ref[0, kj * block_k:(kj + 1) * block_k, :]
            v = v_ref[0, kj * block_k:(kj + 1) * block_k, :]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            ok = (_block_mask(qi, kj, block_q, block_k, causal, seq_len)
                  if masked else None)
            if ok is not None:
                s = jnp.where(ok, s, _NEG_BIG)
            m_prev = m_scr[...]
            block_max = jnp.max(s, axis=1, keepdims=True)
            m_new = jnp.maximum(m_prev, jnp.broadcast_to(block_max,
                                                         m_prev.shape))
            alpha = jnp.exp(m_prev[:, :1] - m_new[:, :1])
            p = jnp.exp(s - m_new[:, :1])
            if ok is not None:
                p = jnp.where(ok, p, 0.0)
            l_new = l_scr[...] * alpha + jnp.broadcast_to(
                jnp.sum(p, axis=1, keepdims=True), l_scr.shape)
            acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_scr[...] = m_new
            l_scr[...] = l_new
        return _compute

    for kj in range(nk):
        live = _live_block(qi, kj, block_q, block_k, causal, seq_len)
        _masked_dispatch(compute_for(kj), live, qi, kj, block_q,
                         block_k, causal, seq_len)

    l = jnp.maximum(l_scr[:, :1], 1e-30)
    o_ref[0] = (acc_scr[...] / l).astype(o_ref.dtype)
    lse_ref[0, 0] = jnp.broadcast_to(m_scr[:, :1] + jnp.log(l),
                                     (block_q, 8))


# The unrolled-KV forward needs the whole (T, D) K and V rows resident
# in VMEM (2 x T*D*itemsize, double-buffered) and emits nk copies of the
# body; beyond these bounds the grid-per-KV-block form takes over.  1 MB
# (T=4096 at D=128 bf16) is the measured limit: at 2 MB rows the full
# model's VMEM budget fails to compile on v5e.
_UNROLL_KV_MAX_BYTES = 1 << 20
_UNROLL_KV_MAX_NK = 16


def _fwd_kernel_fullunroll(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                           scale, causal, block, seq_len, nq, nk):
    """Forward with BOTH loops unrolled inside one (B, H) grid step:
    every (qi, kj) index is a python int, so dead causal/padding blocks
    are skipped at trace time (zero code, zero compute — better than
    ``pl.when``, which still emits and fetches), boundary masks are
    static, and the per-Q-block online-softmax chains are independent
    SSA values with no scratch — Mosaic's scheduler is free to
    interleave one chain's VPU softmax with another's MXU matmul.
    Measured the fastest forward form on v5e for T <= 4k
    (docs/benchmarks.md)."""
    # Whole rows read/written ONCE; per-block tiles are value-level
    # static slices (ref-level partial slices trip the interpreter's vma
    # tracking under shard_map, and a single store is also the friendlier
    # form for Mosaic).
    qfull = q_ref[0]
    kfull = k_ref[0]
    vfull = v_ref[0]
    outs = []
    lses = []
    for qi in range(nq):
        q = lax.slice_in_dim(qfull, qi * block, (qi + 1) * block, axis=0)
        m = jnp.full((block, 1), _NEG_BIG, jnp.float32)
        l = jnp.zeros((block, 1), jnp.float32)
        acc = jnp.zeros((block, qfull.shape[1]), jnp.float32)
        for kj in range(nk):
            if _static_dead(qi, kj, block, causal, seq_len):
                continue
            k = lax.slice_in_dim(kfull, kj * block, (kj + 1) * block,
                                 axis=0)
            v = lax.slice_in_dim(vfull, kj * block, (kj + 1) * block,
                                 axis=0)
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            interior = _static_interior(qi, kj, block, causal, seq_len)
            if not interior:
                ok = _block_mask(qi, kj, block, block, causal, seq_len)
                s = jnp.where(ok, s, _NEG_BIG)
            m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
            alpha = jnp.exp(m - m_new)
            p = jnp.exp(s - m_new)
            if not interior:
                p = jnp.where(ok, p, 0.0)
            l = l * alpha + jnp.sum(p, axis=1, keepdims=True)
            acc = acc * alpha + jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m = m_new
        l_safe = jnp.maximum(l, 1e-30)
        outs.append((acc / l_safe).astype(o_ref.dtype))
        lses.append(jnp.broadcast_to(m + jnp.log(l_safe), (block, 8)))
    o_ref[0] = outs[0] if nq == 1 else jnp.concatenate(outs, axis=0)
    lse_ref[0, 0] = (lses[0] if nq == 1
                     else jnp.concatenate(lses, axis=0))


# VMEM row bound for the opt-in fully-unrolled BACKWARD (see the
# selection comment in _bwd_pallas_packed).
_FULL_UNROLL_BWD_MAX_BYTES = 512 << 10

# Full unrolling emits ~nq*nk/2 bodies and holds whole Q/K/V/O rows in
# VMEM; past these bounds the unrolled-KV and grid forms take over.
# 512-wide tiles measured best (0.625 T^2 executed area vs 0.75 at 1024,
# with enough independent chains to hide the softmax VPU latency).  The
# nq cap bounds code size: small EXPLICIT user blocks would otherwise
# unroll (T/block)^2/2 bodies (T=4096 at block 8 is ~131k dot bodies —
# minutes-to-hours of Mosaic compile); such configs take the grid forms.
_FULL_UNROLL_MAX_T = 4096
_FULL_UNROLL_BLOCK = 512
_FULL_UNROLL_MAX_NQ = 8


def _fwd_packed(q, k, v, H, D, *, scale, causal, block_q, block_k,
                interpret, seq_len=None, head_base=(0, 0, 0)):
    """Forward on head-packed (B, T, C) views (C = H*D): the head is a
    grid axis and every BlockSpec offsets its last dim by ``h*D``, so no
    (B, T, H, D) -> (B*H, T, D) transpose copy ever materializes in HBM
    (measured ~25 ms/step of pure layout copies at the bench shape —
    docs/benchmarks.md).  ``head_base`` shifts each operand's head-block
    offset, letting q/k/v be three regions of ONE fused (B, T, 3*H*D)
    projection (so the qkv split never copies either).  lse comes back
    as (B, H, T)."""
    B, T, _ = q.shape
    nq = T // block_q
    nk = T // block_k
    oq, ok_, ov = head_base
    # The fully-unrolled form re-tiles internally (the tile size is a
    # schedule detail — flash results are block-size independent up to
    # f32 reassociation); fb divides T whenever T is a multiple of 8
    # beyond the tile, else fall through to the other forms.  Under
    # shard_map manual axes IN INTERPRET MODE the generic HLO
    # interpreter cannot discharge this kernel's loads (its vma check
    # rejects the block dynamic_slices), so CPU tests take the
    # unrolled-KV form there; compiled Mosaic is unaffected.
    in_vma = getattr(jax.typeof(q), "vma", None) or frozenset()
    fb = min(_FULL_UNROLL_BLOCK, block_q, block_k, T)
    # Mosaic's stack for the unrolled body scales ~T² (f32 s/p
    # temporaries per live block pair): measured ≤16 MB at T=2048 but
    # 44.4 MB at T=4096, which overflows the default scoped-VMEM budget.
    # Past 2048 the kernel therefore needs a raised budget — resolution
    # order and stand-down semantics live in _flash_fwd_vmem_mb (its
    # own knob, then the shared one with a warning, then the hardware
    # auto-grant).  A budget below the floor stands this form down
    # instead of silently requesting more than asked; the unrolled-KV
    # form below takes over when this one is refused.
    if T <= 2048:
        _fwd_vmem_mb = 0                 # default budget suffices
        _fwd_ok = True
    else:
        _fwd_vmem_mb = _flash_fwd_vmem_mb()
        _fwd_ok = _fwd_vmem_mb >= _FWD_MIN_VMEM_MB
    if (T <= _FULL_UNROLL_MAX_T and T % fb == 0
            and T // fb <= _FULL_UNROLL_MAX_NQ
            and not (interpret and in_vma)
            and T * D * q.dtype.itemsize <= _UNROLL_KV_MAX_BYTES
            and _fwd_ok):
        out, lse = pl.pallas_call(
            functools.partial(_fwd_kernel_fullunroll, scale=scale,
                              causal=causal, block=fb, seq_len=seq_len,
                              nq=T // fb, nk=T // fb),
            grid=(B, H),
            in_specs=[
                pl.BlockSpec((1, T, D), lambda b, h: (b, 0, h + oq)),
                pl.BlockSpec((1, T, D), lambda b, h: (b, 0, h + ok_)),
                pl.BlockSpec((1, T, D), lambda b, h: (b, 0, h + ov)),
            ],
            out_specs=[
                pl.BlockSpec((1, T, D), lambda b, h: (b, 0, h)),
                pl.BlockSpec((1, 1, T, 8), lambda b, h: (b, h, 0, 0)),
            ],
            out_shape=[
                _struct((B, T, H * D), q.dtype, q, k, v),
                _struct((B, H, T, 8), jnp.float32, q, k, v),
            ],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel"),
                **({"vmem_limit_bytes": _fwd_vmem_mb * 1024 * 1024}
                   if _fwd_vmem_mb else {})),
            interpret=interpret,
        )(q, k, v)
        return out, lse[..., 0]
    if (nk <= _UNROLL_KV_MAX_NK
            and T * D * q.dtype.itemsize <= _UNROLL_KV_MAX_BYTES):
        out, lse = pl.pallas_call(
            functools.partial(_fwd_kernel_unrollkv, scale=scale,
                              causal=causal, block_q=block_q,
                              block_k=block_k, seq_len=seq_len, nk=nk),
            grid=(B, H, nq),
            in_specs=[
                pl.BlockSpec((1, block_q, D),
                             lambda b, h, i: (b, i, h + oq)),
                pl.BlockSpec((1, T, D), lambda b, h, i: (b, 0, h + ok_)),
                pl.BlockSpec((1, T, D), lambda b, h, i: (b, 0, h + ov)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_q, D), lambda b, h, i: (b, i, h)),
                pl.BlockSpec((1, 1, block_q, 8),
                             lambda b, h, i: (b, h, i, 0)),
            ],
            out_shape=[
                _struct((B, T, H * D), q.dtype, q, k, v),
                _struct((B, H, T, 8), jnp.float32, q, k, v),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_q, 128), jnp.float32),
                pltpu.VMEM((block_q, 128), jnp.float32),
                pltpu.VMEM((block_q, D), jnp.float32),
            ],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "parallel")),
            interpret=interpret,
        )(q, k, v)
        return out, lse[..., 0]
    grid = (B, H, nq, nk)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k,
                               seq_len=seq_len, axes=(2, 3))
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D),
                         lambda b, h, i, j: (b, i, h + oq)),
            pl.BlockSpec((1, block_k, D),
                         lambda b, h, i, j: (b, j, h + ok_)),
            pl.BlockSpec((1, block_k, D),
                         lambda b, h, i, j: (b, j, h + ov)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, h, i, j: (b, i, h)),
            pl.BlockSpec((1, 1, block_q, 8),
                         lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_shape=[
            _struct((B, T, H * D), q.dtype, q, k, v),
            _struct((B, H, T, 8), jnp.float32, q, k, v),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v)
    return out, lse[..., 0]


def _bwd_xla(q, k, v, o, lse, do, *, scale, causal, chunk, seq_len=None):
    """Flash backward with blockwise XLA einsums over KV chunks: linear
    memory, uses the saved logsumexp (no softmax recompute instability)."""
    BH, T, D = q.shape
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    delta = jnp.sum(dof * o.astype(jnp.float32), axis=-1)     # (BH, T)
    rows = jnp.arange(T)

    def one_chunk(dq_acc, start):
        ks = lax.dynamic_slice_in_dim(kf, start, chunk, axis=1)
        vs = lax.dynamic_slice_in_dim(vf, start, chunk, axis=1)
        cols = start + jnp.arange(chunk)
        s = jnp.einsum("btd,bcd->btc", qf, ks) * scale
        mask = None
        if causal:
            mask = cols[None, :] <= rows[:, None]             # (T, chunk)
        if seq_len is not None:
            lim = jnp.logical_and(rows[:, None] < seq_len,
                                  cols[None, :] < seq_len)
            mask = lim if mask is None else jnp.logical_and(mask, lim)
        if mask is not None:
            s = jnp.where(mask[None], s, _NEG_BIG)
        p = jnp.exp(s - lse[..., None])                       # (BH, T, c)
        if mask is not None:
            p = jnp.where(mask[None], p, 0.0)
        dp = jnp.einsum("btd,bcd->btc", dof, vs)
        ds = p * (dp - delta[..., None]) * scale
        # dq accumulates across chunks in the scan carry (keeping per-chunk
        # dq stacked would be the O(T^2) buffer this path exists to avoid);
        # dk/dv tile the T axis, so stacking them is linear.
        dq_acc = dq_acc + jnp.einsum("btc,bcd->btd", ds, ks)
        dk_c = jnp.einsum("btc,btd->bcd", ds, qf)
        dv_c = jnp.einsum("btc,btd->bcd", p, dof)
        return dq_acc, (dk_c, dv_c)

    starts = jnp.arange(0, T, chunk)
    dq, (dk_chunks, dv_chunks) = lax.scan(
        one_chunk, jnp.zeros_like(qf), starts)
    dk = dk_chunks.transpose(1, 0, 2, 3).reshape(BH, T, D)
    dv = dv_chunks.transpose(1, 0, 2, 3).reshape(BH, T, D)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dta_ref,
                 dk_ref, dv_ref, dk_scr, dv_scr, *,
                 scale, causal, block_q, block_k, seq_len, axes=(1, 2)):
    """Accumulate dk/dv for one KV block while Q blocks stream through
    (grid innermost axis).  The flash-backward identities:
    p = exp(s - lse);  dv += p^T dO;  dS = p * (dO V^T - delta) * scale;
    dk += dS^T Q."""
    kj = pl.program_id(axes[0])
    qi = pl.program_id(axes[1])
    nq = pl.num_programs(axes[1])
    row8 = (0, 0) if lse_ref.ndim == 4 else (0,)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    def _compute(masked: bool):
        q = q_ref[0]                                   # (BQ, D)
        k = k_ref[0]                                   # (BK, D)
        v = v_ref[0]                                   # (BK, D)
        do = do_ref[0]                                 # (BQ, D)
        lse = lse_ref[row8][:, :1]                     # (BQ, 1)
        delta = dta_ref[row8][:, :1]                   # (BQ, 1)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale    # (BQ, BK)
        p = jnp.exp(s - lse)
        ok = (_block_mask(qi, kj, block_q, block_k, causal, seq_len)
              if masked else None)
        if ok is not None:
            p = jnp.where(ok, p, 0.0)
        # dv += p^T @ dO — p cast to the input dtype so the MXU runs at
        # native rate; all accumulation stays f32.
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # (BQ, BK)
        ds = p * (dp - delta) * scale
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    live = _live_block(qi, kj, block_q, block_k, causal, seq_len)
    _masked_dispatch(_compute, live, qi, kj, block_q, block_k, causal,
                     seq_len)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dta_ref,
               dq_ref, dq_scr, *, scale, causal, block_q, block_k,
               seq_len, axes=(1, 2)):
    """Accumulate dq for one Q block while KV blocks stream through:
    dq += dS @ K with dS = p * (dO V^T - delta) * scale."""
    qi = pl.program_id(axes[0])
    kj = pl.program_id(axes[1])
    nk = pl.num_programs(axes[1])
    row8 = (0, 0) if lse_ref.ndim == 4 else (0,)

    @pl.when(kj == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    def _compute(masked: bool):
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[row8][:, :1]
        delta = dta_ref[row8][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lse)
        ok = (_block_mask(qi, kj, block_q, block_k, causal, seq_len)
              if masked else None)
        if ok is not None:
            p = jnp.where(ok, p, 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_scr[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    live = _live_block(qi, kj, block_q, block_k, causal, seq_len)
    _masked_dispatch(_compute, live, qi, kj, block_q, block_k, causal,
                     seq_len)

    @pl.when(kj == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dta_ref,
                      dk_ref, dv_ref, dq_ref, dk_scr, dv_scr, dq_scr, *,
                      scale, causal, block_q, block_k, seq_len):
    """Single-pass flash backward: dk/dv accumulate per KV block while Q
    blocks stream (inner grid axis), and dq accumulates into a
    full-sequence f32 VMEM scratch, so the ``s``/``p``/``dp`` recompute
    the two-kernel split pays twice is computed once — 5 block matmuls
    per pair instead of 7:
    p = exp(s - lse);  dv += p^T dO;  dp = dO V^T;
    dS = p * (dp - delta) * scale;  dk += dS^T Q;  dq += dS K."""
    kj = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init_kv():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    # dq scratch is (nq, block_q, D) — dynamic indexing stays on the
    # leading (tile) dim, which Mosaic lowers to plain tile addressing
    # (a dynamic sublane slice of a flat (T, D) scratch lowered ~2x
    # slower on v5e).
    # The dq slice for this Q block is zeroed on the first KV pass even
    # when the block pair is dead (padding tail), so the unconditional
    # output write below never flushes stale scratch.
    @pl.when(kj == 0)
    def _init_dq():
        dq_scr[qi] = jnp.zeros_like(dq_scr[qi])

    def _compute(masked: bool):
        q = q_ref[0]                                   # (BQ, D)
        k = k_ref[0]                                   # (BK, D)
        v = v_ref[0]                                   # (BK, D)
        do = do_ref[0]                                 # (BQ, D)
        lse = lse_ref[0][:, :1]                        # (BQ, 1)
        delta = dta_ref[0][:, :1]                      # (BQ, 1)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale    # (BQ, BK)
        p = jnp.exp(s - lse)
        ok = (_block_mask(qi, kj, block_q, block_k, causal, seq_len)
              if masked else None)
        if ok is not None:
            p = jnp.where(ok, p, 0.0)
        # Operands cast to the input dtype so the MXU runs at native
        # rate; every accumulator stays f32.
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # (BQ, BK)
        ds = p * (dp - delta) * scale
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dq_scr[qi] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    live = _live_block(qi, kj, block_q, block_k, causal, seq_len)
    _masked_dispatch(_compute, live, qi, kj, block_q, block_k, causal,
                     seq_len)

    @pl.when(qi == nq - 1)
    def _finalize_kv():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)

    # dq is only complete after the last KV pass; earlier writes flush
    # partial sums that the final pass overwrites (a (BQ, D) VMEM copy
    # per step — noise next to the block matmuls).
    dq_ref[0] = dq_scr[qi].astype(dq_ref.dtype)


# Widest dq scratch the fused backward may allocate: f32 full-sequence
# accumulator.  4 MB = T 8192 at D=128 — past that the split two-kernel
# path takes over (ring/Ulysses shard T across chips long before then).
_FUSED_DQ_SCRATCH_BYTES = 4 << 20


def _bwd_pallas_fused(q, k, v, o, lse, do, *, scale, causal, block_q,
                      block_k, interpret, seq_len=None):
    """Fused one-pass flash backward (see :func:`_bwd_fused_kernel`)."""
    BH, T, D = q.shape
    nq = T // block_q
    nk = T // block_k
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)                                   # (BH, T)
    lse8 = jnp.broadcast_to(lse[..., None], (BH, T, 8))
    delta8 = jnp.broadcast_to(delta[..., None], (BH, T, 8))

    specs = dict(
        q=pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0)),
        kv=pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
        row8=pl.BlockSpec((1, block_q, 8), lambda b, j, i: (b, i, 0)),
    )
    dk, dv, dq = pl.pallas_call(
        functools.partial(_bwd_fused_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          seq_len=seq_len),
        grid=(BH, nk, nq),
        in_specs=[specs["q"], specs["kv"], specs["kv"],
                  specs["q"], specs["row8"], specs["row8"]],
        out_specs=[specs["kv"], specs["kv"], specs["q"]],
        out_shape=[_struct((BH, T, D), k.dtype, q, k, v, do),
                   _struct((BH, T, D), v.dtype, q, k, v, do),
                   _struct((BH, T, D), q.dtype, q, k, v, do)],
        scratch_shapes=[pltpu.VMEM((block_k, D), jnp.float32),
                        pltpu.VMEM((block_k, D), jnp.float32),
                        pltpu.VMEM((nq, block_q, D), jnp.float32)],
        # The KV axis carries the dq accumulator across steps, so it is
        # "arbitrary" here (it was "parallel" in the split dkdv kernel).
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse8, delta8)
    return dq, dk, dv


def _bwd_pallas(q, k, v, o, lse, do, *, scale, causal, block_q, block_k,
                interpret, seq_len=None):
    """Flash backward as two Pallas kernels with the forward's
    VMEM-resident blockwise schedule (FlashAttention-2 backward split)."""
    BH, T, D = q.shape
    nq = T // block_q
    nk = T // block_k
    # Per-row delta = rowsum(dO * O) and lse, broadcast to the (BQ, 8)
    # narrow-tile layout the forward uses for its lse output.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)                                   # (BH, T)
    lse8 = jnp.broadcast_to(lse[..., None], (BH, T, 8))
    delta8 = jnp.broadcast_to(delta[..., None], (BH, T, 8))

    row_specs = dict(
        q=pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0)),
        kv=pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
        row8=pl.BlockSpec((1, block_q, 8), lambda b, j, i: (b, i, 0)),
    )
    dk, dv = pl.pallas_call(
        functools.partial(_dkdv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          seq_len=seq_len),
        grid=(BH, nk, nq),
        in_specs=[row_specs["q"], row_specs["kv"], row_specs["kv"],
                  row_specs["q"], row_specs["row8"], row_specs["row8"]],
        out_specs=[row_specs["kv"], row_specs["kv"]],
        out_shape=[_struct((BH, T, D), k.dtype, q, k, v, do),
                   _struct((BH, T, D), v.dtype, q, k, v, do)],
        scratch_shapes=[pltpu.VMEM((block_k, D), jnp.float32),
                        pltpu.VMEM((block_k, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse8, delta8)

    q_specs = dict(
        q=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        kv=pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        row8=pl.BlockSpec((1, block_q, 8), lambda b, i, j: (b, i, 0)),
    )
    dq, = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          seq_len=seq_len),
        grid=(BH, nq, nk),
        in_specs=[q_specs["q"], q_specs["kv"], q_specs["kv"],
                  q_specs["q"], q_specs["row8"], q_specs["row8"]],
        out_specs=[q_specs["q"]],
        out_shape=[_struct((BH, T, D), q.dtype, q, k, v, do)],
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse8, delta8)
    return dq, dk, dv


def _dkdv_kernel_grouped(q_ref, k_ref, v_ref, do_ref, lse_ref, dta_ref,
                         dk_ref, dv_ref, dk_scr, dv_scr, *, scale, causal,
                         block_q, block_k, seq_len, group, head_dim):
    """Head-GROUP blocked dk/dv: each tile spans ``group`` adjacent heads
    ((block, group*D) — HBM rows ``group``× wider than the per-head
    packed kernel's 256-byte strided reads), with per-head math on
    128-aligned lane slices inside VMEM.  Same schedule as
    :func:`_dkdv_kernel` otherwise."""
    kj = pl.program_id(2)
    qi = pl.program_id(3)
    nq = pl.num_programs(3)
    D = head_dim

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    def _compute(masked: bool):
        ok = (_block_mask(qi, kj, block_q, block_k, causal, seq_len)
              if masked else None)
        for g in range(group):
            sl = slice(g * D, (g + 1) * D)
            q = q_ref[0][:, sl]                        # (BQ, D)
            k = k_ref[0][:, sl]                        # (BK, D)
            v = v_ref[0][:, sl]                        # (BK, D)
            do = do_ref[0][:, sl]                      # (BQ, D)
            lse = lse_ref[0, g][:, :1]                 # (BQ, 1)
            delta = dta_ref[0, g][:, :1]               # (BQ, 1)
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            p = jnp.exp(s - lse)
            if ok is not None:
                p = jnp.where(ok, p, 0.0)
            dv_scr[g] += jax.lax.dot_general(
                p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            ds = p * (dp - delta) * scale
            dk_scr[g] += jax.lax.dot_general(
                ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    live = _live_block(qi, kj, block_q, block_k, causal, seq_len)
    _masked_dispatch(_compute, live, qi, kj, block_q, block_k, causal,
                     seq_len)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = jnp.concatenate(
            [dk_scr[g] for g in range(group)], axis=1).astype(dk_ref.dtype)
        dv_ref[0] = jnp.concatenate(
            [dv_scr[g] for g in range(group)], axis=1).astype(dv_ref.dtype)


def _dq_kernel_grouped(q_ref, k_ref, v_ref, do_ref, lse_ref, dta_ref,
                       dq_ref, dq_scr, *, scale, causal, block_q, block_k,
                       seq_len, group, head_dim):
    """Head-group blocked dq accumulation (see
    :func:`_dkdv_kernel_grouped`)."""
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    nk = pl.num_programs(3)
    D = head_dim

    @pl.when(kj == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    def _compute(masked: bool):
        ok = (_block_mask(qi, kj, block_q, block_k, causal, seq_len)
              if masked else None)
        for g in range(group):
            sl = slice(g * D, (g + 1) * D)
            q = q_ref[0][:, sl]
            k = k_ref[0][:, sl]
            v = v_ref[0][:, sl]
            do = do_ref[0][:, sl]
            lse = lse_ref[0, g][:, :1]
            delta = dta_ref[0, g][:, :1]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            p = jnp.exp(s - lse)
            if ok is not None:
                p = jnp.where(ok, p, 0.0)
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            ds = p * (dp - delta) * scale
            dq_scr[g] += jax.lax.dot_general(
                ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    live = _live_block(qi, kj, block_q, block_k, causal, seq_len)
    _masked_dispatch(_compute, live, qi, kj, block_q, block_k, causal,
                     seq_len)

    @pl.when(kj == nk - 1)
    def _finalize():
        dq_ref[0] = jnp.concatenate(
            [dq_scr[g] for g in range(group)], axis=1).astype(dq_ref.dtype)


def _bwd_pallas_packed_grouped(q, k, v, o, lse, do, H, D, group, *, scale,
                               causal, block_q, block_k, interpret,
                               seq_len, head_base):
    """Head-group blocked split backward on head-packed (B, T, C) views:
    the strided 256-byte-row tax of the per-head packed kernels
    (measured ~12 ms/step at the bench shape, docs/benchmarks.md) is
    removed by reading ``group`` adjacent heads per tile — contiguous
    ``group*D``-wide rows — while keeping the copies-free packed layout."""
    B, T, _ = q.shape
    C = H * D
    nq = T // block_q
    nk = T // block_k
    HG = H // group
    oq, ok_, ov = (b // group for b in head_base)
    delta = jnp.sum((do.astype(jnp.float32)
                     * o.astype(jnp.float32)).reshape(B, T, H, D),
                    axis=-1).transpose(0, 2, 1)               # (B, H, T)
    lse8 = jnp.broadcast_to(lse[..., None], (B, H, T, 8))
    delta8 = jnp.broadcast_to(delta[..., None], (B, H, T, 8))
    GD = group * D

    kv_specs = dict(
        q=pl.BlockSpec((1, block_q, GD),
                       lambda b, h, j, i: (b, i, h + oq)),
        k=pl.BlockSpec((1, block_k, GD),
                       lambda b, h, j, i: (b, j, h + ok_)),
        v=pl.BlockSpec((1, block_k, GD),
                       lambda b, h, j, i: (b, j, h + ov)),
        do=pl.BlockSpec((1, block_q, GD), lambda b, h, j, i: (b, i, h)),
        out=pl.BlockSpec((1, block_k, GD), lambda b, h, j, i: (b, j, h)),
        row8=pl.BlockSpec((1, group, block_q, 8),
                          lambda b, h, j, i: (b, h, i, 0)),
    )
    # The r4 A/B's block-1024 grouped configs died on Mosaic's default
    # scoped-VMEM budget (18.11 M > 16 M) — the f32 score temporaries
    # double with two heads live.  v5e has 128 MB of VMEM, so the limit
    # is policy, not hardware: the grouped pair defaults to a 32 MB
    # per-kernel budget (measured sufficient for g2 at 1024² blocks and
    # the margin of the win); HOROVOD_TPU_FLASH_VMEM_MB overrides, 0
    # restores the compiler default.
    _vmem_mb = _flash_vmem_mb()
    _sem_kw = {"dimension_semantics": ("parallel", "parallel", "parallel",
                                       "arbitrary")}
    if _vmem_mb:
        _sem_kw["vmem_limit_bytes"] = _vmem_mb * 1024 * 1024
    sem4 = pltpu.CompilerParams(**_sem_kw)
    dk, dv = pl.pallas_call(
        functools.partial(_dkdv_kernel_grouped, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          seq_len=seq_len, group=group, head_dim=D),
        grid=(B, HG, nk, nq),
        in_specs=[kv_specs["q"], kv_specs["k"], kv_specs["v"],
                  kv_specs["do"], kv_specs["row8"], kv_specs["row8"]],
        out_specs=[kv_specs["out"], kv_specs["out"]],
        out_shape=[_struct((B, T, C), k.dtype, q, k, v, do),
                   _struct((B, T, C), v.dtype, q, k, v, do)],
        scratch_shapes=[pltpu.VMEM((group, block_k, D), jnp.float32),
                        pltpu.VMEM((group, block_k, D), jnp.float32)],
        compiler_params=sem4,
        interpret=interpret,
    )(q, k, v, do, lse8, delta8)

    q_specs = dict(
        q=pl.BlockSpec((1, block_q, GD),
                       lambda b, h, i, j: (b, i, h + oq)),
        k=pl.BlockSpec((1, block_k, GD),
                       lambda b, h, i, j: (b, j, h + ok_)),
        v=pl.BlockSpec((1, block_k, GD),
                       lambda b, h, i, j: (b, j, h + ov)),
        do=pl.BlockSpec((1, block_q, GD), lambda b, h, i, j: (b, i, h)),
        out=pl.BlockSpec((1, block_q, GD), lambda b, h, i, j: (b, i, h)),
        row8=pl.BlockSpec((1, group, block_q, 8),
                          lambda b, h, i, j: (b, h, i, 0)),
    )
    dq, = pl.pallas_call(
        functools.partial(_dq_kernel_grouped, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          seq_len=seq_len, group=group, head_dim=D),
        grid=(B, HG, nq, nk),
        in_specs=[q_specs["q"], q_specs["k"], q_specs["v"],
                  q_specs["do"], q_specs["row8"], q_specs["row8"]],
        out_specs=[q_specs["out"]],
        out_shape=[_struct((B, T, C), q.dtype, q, k, v, do)],
        scratch_shapes=[pltpu.VMEM((group, block_q, D), jnp.float32)],
        compiler_params=sem4,
        interpret=interpret,
    )(q, k, v, do, lse8, delta8)
    return dq, dk, dv


def _bwd_kernel_fullunroll(q_ref, k_ref, v_ref, do_ref, lse_ref, dta_ref,
                           dq_ref, dk_ref, dv_ref, *, scale, causal,
                           block, seq_len, nq, nk):
    """One-pass flash backward with BOTH loops unrolled on a (B, H)
    grid: every (qi, kj) is a python int, so each live pair's
    s/p/dp/ds are computed ONCE and contracted into dq AND dk/dv — the
    5-matmul fused schedule that the grid-looped fused kernel could not
    make fast (its loop-carried dq scratch serialized Mosaic's
    pipeline; here everything is independent SSA, nothing carries).
    Dead causal/padding pairs are skipped at trace time and boundary
    masks are static, like :func:`_fwd_kernel_fullunroll`."""
    qfull = q_ref[0]
    kfull = k_ref[0]
    vfull = v_ref[0]
    dofull = do_ref[0]
    lse_rows = lse_ref[0, 0][:, :1]                       # (T, 1)
    dta_rows = dta_ref[0, 0][:, :1]                       # (T, 1)
    D = qfull.shape[1]
    dq_parts = [jnp.zeros((block, D), jnp.float32) for _ in range(nq)]
    dk_parts = [jnp.zeros((block, D), jnp.float32) for _ in range(nk)]
    dv_parts = [jnp.zeros((block, D), jnp.float32) for _ in range(nk)]
    for kj in range(nk):
        k = lax.slice_in_dim(kfull, kj * block, (kj + 1) * block, axis=0)
        v = lax.slice_in_dim(vfull, kj * block, (kj + 1) * block, axis=0)
        for qi in range(nq):
            if _static_dead(qi, kj, block, causal, seq_len):
                continue
            q = lax.slice_in_dim(qfull, qi * block, (qi + 1) * block,
                                 axis=0)
            do = lax.slice_in_dim(dofull, qi * block, (qi + 1) * block,
                                  axis=0)
            lse = lax.slice_in_dim(lse_rows, qi * block,
                                   (qi + 1) * block, axis=0)
            delta = lax.slice_in_dim(dta_rows, qi * block,
                                     (qi + 1) * block, axis=0)
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            p = jnp.exp(s - lse)
            interior = _static_interior(qi, kj, block, causal, seq_len)
            if not interior:
                ok = _block_mask(qi, kj, block, block, causal, seq_len)
                p = jnp.where(ok, p, 0.0)
            dv_parts[kj] = dv_parts[kj] + jax.lax.dot_general(
                p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            ds = p * (dp - delta) * scale
            dk_parts[kj] = dk_parts[kj] + jax.lax.dot_general(
                ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dq_parts[qi] = dq_parts[qi] + jax.lax.dot_general(
                ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    def cat(parts, dtype):
        parts = [p.astype(dtype) for p in parts]
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts, 0)

    dq_ref[0] = cat(dq_parts, dq_ref.dtype)
    dk_ref[0] = cat(dk_parts, dk_ref.dtype)
    dv_ref[0] = cat(dv_parts, dv_ref.dtype)


def _bwd_pallas_packed(q, k, v, o, lse, do, H, D, *, scale, causal,
                       block_q, block_k, interpret, seq_len=None,
                       head_base=(0, 0, 0)):
    """Split flash backward on head-packed (B, T, C) views (see
    :func:`_fwd_packed`); ``lse`` arrives as (B, H, T) and ``o``/``do``
    are head-merged (B, T, H*D).

    The packed kernels read strided 256-byte rows (measured ~+1 ms/layer
    over contiguous tiles on v5e at the bench shape, vs ~+0.8 ms/layer
    of transpose copies for the merged layout) — the strided form stays
    the default; ``HOROVOD_TPU_FLASH_PACKED_BWD=0`` switches to
    transpose-to-merged + the contiguous kernel pair for A/B."""
    B, T, _ = q.shape
    if os.environ.get("HOROVOD_TPU_FLASH_PACKED_BWD", "1") == "0":
        oq, ok_, ov = head_base

        def pick(x, off):   # (B, T, C*) head range -> merged (B*H, T, D)
            x = x[..., off * D:(off + H) * D]
            return (x.reshape(B, T, H, D).transpose(0, 2, 1, 3)
                    .reshape(B * H, T, D))

        qm, km, vm = pick(q, oq), pick(k, ok_), pick(v, ov)
        om, dom = pick(o, 0), pick(do, 0)
        dqm, dkm, dvm = _bwd_pallas(
            qm, km, vm, om, lse.reshape(B * H, T), dom, scale=scale,
            causal=causal, block_q=block_q, block_k=block_k,
            interpret=interpret, seq_len=seq_len)

        def unpick(g):
            return (g.reshape(B, H, T, D).transpose(0, 2, 1, 3)
                    .reshape(B, T, H * D))

        return unpick(dqm), unpick(dkm), unpick(dvm)
    # Head-group blocked variant (VERDICT r4 weak #3): tiles span
    # `group` adjacent heads so the HBM rows are group× wider than the
    # per-head 256-byte strided reads.  Requires group | H and
    # group-aligned head bases (the fused-qkv bases 0/H/2H qualify
    # whenever group | H).  The r4 A/B that rejected it hit Mosaic's
    # default 16 MB scoped-VMEM budget at block 1024; with the budget
    # raised (HOROVOD_TPU_FLASH_VMEM_MB, default 32 for grouped) g2 at
    # 1024² measures 11.97 vs 12.18 ms/layer-iter on v5e — so g2 is the
    # DEFAULT at exactly that proven shape (both blocks 1024, D=128);
    # everywhere else per-head remains default and the env opts in.
    # Auto-selection stands down when (a) HOROVOD_TPU_FLASH_BWD names an
    # explicit backward impl (the fullunroll A/B would be silently
    # shadowed by the early grouped return), or (b) the device
    # generation cannot back the ~18 MB budget (v2/v3 have 16 MB of
    # physical VMEM per core; v4+ have 128 MB).
    group_env = os.environ.get("HOROVOD_TPU_FLASH_BWD_GROUP")
    if group_env is not None:
        try:
            group = int(group_env)
            if group < 1:
                raise ValueError
        except ValueError:
            import warnings
            warnings.warn(
                f"HOROVOD_TPU_FLASH_BWD_GROUP={group_env!r} is not a "
                "positive integer; using the per-head default (1)",
                RuntimeWarning, stacklevel=2)
            group = 1
    elif (block_q == 1024 and block_k == 1024 and D == 128
          and H % 2 == 0 and all(b % 2 == 0 for b in head_base)
          and os.environ.get("HOROVOD_TPU_FLASH_BWD") is None
          and _flash_vmem_mb() >= 32 and _vmem_headroom_ok()):
        group = 2
    else:
        group = 1
    if (group > 1 and H % group == 0
            and all(b % group == 0 for b in head_base)):
        return _bwd_pallas_packed_grouped(
            q, k, v, o, lse, do, H, D, group, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, interpret=interpret,
            seq_len=seq_len, head_base=head_base)
    C = H * D
    nq = T // block_q
    nk = T // block_k
    oq, ok_, ov = head_base
    # Per-head delta = rowsum(dO * O): reduce D inside each head.
    delta = jnp.sum((do.astype(jnp.float32)
                     * o.astype(jnp.float32)).reshape(B, T, H, D),
                    axis=-1).transpose(0, 2, 1)               # (B, H, T)
    lse8 = jnp.broadcast_to(lse[..., None], (B, H, T, 8))
    delta8 = jnp.broadcast_to(delta[..., None], (B, H, T, 8))

    # The fused one-pass form (5 matmuls/pair instead of the split
    # pair's 7) measured a WASH on v5e (5.24 vs 5.19 ms f+b at the
    # bench shape) — whatever binds the backward, it isn't matmul
    # count.  Kept behind an env knob so the recorded A/B stays
    # reproducible; the split pair stays the measured default.
    in_vma = getattr(jax.typeof(q), "vma", None) or frozenset()
    fbb = min(_FULL_UNROLL_BLOCK, block_q, block_k, T)
    # Tighter VMEM bound than the forward's: this kernel holds 4 input
    # + 3 output full rows PLUS three full-sequence f32 accumulator
    # part-sets, several times the forward's residency — 512 KB rows
    # (T=2048 at D=128 bf16, the measured-working shape) is the limit.
    if (os.environ.get("HOROVOD_TPU_FLASH_BWD") == "fullunroll"
            and T <= _FULL_UNROLL_MAX_T and T % fbb == 0
            and T // fbb <= _FULL_UNROLL_MAX_NQ
            and not (interpret and in_vma)
            and T * D * q.dtype.itemsize <= _FULL_UNROLL_BWD_MAX_BYTES):
        n = T // fbb
        dq, dk, dv = pl.pallas_call(
            functools.partial(_bwd_kernel_fullunroll, scale=scale,
                              causal=causal, block=fbb, seq_len=seq_len,
                              nq=n, nk=n),
            grid=(B, H),
            in_specs=[
                pl.BlockSpec((1, T, D), lambda b, h: (b, 0, h + oq)),
                pl.BlockSpec((1, T, D), lambda b, h: (b, 0, h + ok_)),
                pl.BlockSpec((1, T, D), lambda b, h: (b, 0, h + ov)),
                pl.BlockSpec((1, T, D), lambda b, h: (b, 0, h)),
                pl.BlockSpec((1, 1, T, 8), lambda b, h: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, T, 8), lambda b, h: (b, h, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, T, D), lambda b, h: (b, 0, h)),
                pl.BlockSpec((1, T, D), lambda b, h: (b, 0, h)),
                pl.BlockSpec((1, T, D), lambda b, h: (b, 0, h)),
            ],
            out_shape=[_struct((B, T, C), q.dtype, q, k, v, do),
                       _struct((B, T, C), k.dtype, q, k, v, do),
                       _struct((B, T, C), v.dtype, q, k, v, do)],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel")),
            interpret=interpret,
        )(q, k, v, do, lse8, delta8)
        return dq, dk, dv

    kv_specs = dict(
        q=pl.BlockSpec((1, block_q, D),
                       lambda b, h, j, i: (b, i, h + oq)),
        k=pl.BlockSpec((1, block_k, D),
                       lambda b, h, j, i: (b, j, h + ok_)),
        v=pl.BlockSpec((1, block_k, D),
                       lambda b, h, j, i: (b, j, h + ov)),
        do=pl.BlockSpec((1, block_q, D), lambda b, h, j, i: (b, i, h)),
        out=pl.BlockSpec((1, block_k, D), lambda b, h, j, i: (b, j, h)),
        row8=pl.BlockSpec((1, 1, block_q, 8),
                          lambda b, h, j, i: (b, h, i, 0)),
    )
    sem4 = pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "parallel",
                             "arbitrary"))
    dk, dv = pl.pallas_call(
        functools.partial(_dkdv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          seq_len=seq_len, axes=(2, 3)),
        grid=(B, H, nk, nq),
        in_specs=[kv_specs["q"], kv_specs["k"], kv_specs["v"],
                  kv_specs["do"], kv_specs["row8"], kv_specs["row8"]],
        out_specs=[kv_specs["out"], kv_specs["out"]],
        out_shape=[_struct((B, T, C), k.dtype, q, k, v, do),
                   _struct((B, T, C), v.dtype, q, k, v, do)],
        scratch_shapes=[pltpu.VMEM((block_k, D), jnp.float32),
                        pltpu.VMEM((block_k, D), jnp.float32)],
        compiler_params=sem4,
        interpret=interpret,
    )(q, k, v, do, lse8, delta8)

    q_specs = dict(
        q=pl.BlockSpec((1, block_q, D),
                       lambda b, h, i, j: (b, i, h + oq)),
        k=pl.BlockSpec((1, block_k, D),
                       lambda b, h, i, j: (b, j, h + ok_)),
        v=pl.BlockSpec((1, block_k, D),
                       lambda b, h, i, j: (b, j, h + ov)),
        do=pl.BlockSpec((1, block_q, D), lambda b, h, i, j: (b, i, h)),
        out=pl.BlockSpec((1, block_q, D), lambda b, h, i, j: (b, i, h)),
        row8=pl.BlockSpec((1, 1, block_q, 8),
                          lambda b, h, i, j: (b, h, i, 0)),
    )
    dq, = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          seq_len=seq_len, axes=(2, 3)),
        grid=(B, H, nq, nk),
        in_specs=[q_specs["q"], q_specs["k"], q_specs["v"],
                  q_specs["do"], q_specs["row8"], q_specs["row8"]],
        out_specs=[q_specs["out"]],
        out_shape=[_struct((B, T, C), q.dtype, q, k, v, do)],
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=sem4,
        interpret=interpret,
    )(q, k, v, do, lse8, delta8)
    return dq, dk, dv


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10, 11))
def _flash_packed(q, k, v, H, scale, causal, block_q, block_k,
                  bwd_block_q, bwd_block_k, interpret, seq_len):
    D = q.shape[2] // H
    out, _ = _fwd_packed(q, k, v, H, D, scale=scale, causal=causal,
                         block_q=block_q, block_k=block_k,
                         interpret=interpret, seq_len=seq_len)
    return out


def _flash_packed_fwd(q, k, v, H, scale, causal, block_q, block_k,
                      bwd_block_q, bwd_block_k, interpret, seq_len):
    D = q.shape[2] // H
    out, lse = _fwd_packed(q, k, v, H, D, scale=scale, causal=causal,
                           block_q=block_q, block_k=block_k,
                           interpret=interpret, seq_len=seq_len)
    return out, (q, k, v, out, lse)


def _flash_packed_bwd(H, scale, causal, block_q, block_k, bwd_block_q,
                      bwd_block_k, interpret, seq_len, res, do):
    q, k, v, o, lse = res
    D = q.shape[2] // H
    return _bwd_pallas_packed(q, k, v, o, lse, do, H, D, scale=scale,
                              causal=causal, block_q=bwd_block_q,
                              block_k=bwd_block_k, interpret=interpret,
                              seq_len=seq_len)


_flash_packed.defvjp(_flash_packed_fwd, _flash_packed_bwd)


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(1, 2, 3, 4, 5, 6, 7, 8, 9))
def _flash_qkv(qkv, H, scale, causal, block_q, block_k, bwd_block_q,
               bwd_block_k, interpret, seq_len):
    D = qkv.shape[2] // (3 * H)
    out, _ = _fwd_packed(qkv, qkv, qkv, H, D, scale=scale, causal=causal,
                         block_q=block_q, block_k=block_k,
                         interpret=interpret, seq_len=seq_len,
                         head_base=(0, H, 2 * H))
    return out


def _flash_qkv_fwd(qkv, H, scale, causal, block_q, block_k, bwd_block_q,
                   bwd_block_k, interpret, seq_len):
    D = qkv.shape[2] // (3 * H)
    out, lse = _fwd_packed(qkv, qkv, qkv, H, D, scale=scale,
                           causal=causal, block_q=block_q,
                           block_k=block_k, interpret=interpret,
                           seq_len=seq_len, head_base=(0, H, 2 * H))
    return out, (qkv, out, lse)


def _flash_qkv_bwd(H, scale, causal, block_q, block_k, bwd_block_q,
                   bwd_block_k, interpret, seq_len, res, do):
    qkv, o, lse = res
    D = qkv.shape[2] // (3 * H)
    dq, dk, dv = _bwd_pallas_packed(
        qkv, qkv, qkv, o, lse, do, H, D, scale=scale, causal=causal,
        block_q=bwd_block_q, block_k=bwd_block_k, interpret=interpret,
        seq_len=seq_len, head_base=(0, H, 2 * H))
    return (jnp.concatenate([dq, dk, dv], axis=-1),)


_flash_qkv.defvjp(_flash_qkv_fwd, _flash_qkv_bwd)


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(2, 3, 4, 5, 6, 7, 8, 9, 10))
def _flash_qkv_proj(x, w, H, scale, causal, block_q, block_k,
                    bwd_block_q, bwd_block_k, interpret, seq_len):
    out, _ = _flash_qkv_proj_fwd(x, w, H, scale, causal, block_q,
                                 block_k, bwd_block_q, bwd_block_k,
                                 interpret, seq_len)
    return out


def _flash_qkv_proj_fwd(x, w, H, scale, causal, block_q, block_k,
                        bwd_block_q, bwd_block_k, interpret, seq_len):
    D = w.shape[1] // (3 * H)
    qkv = jax.lax.dot_general(
        x, w.astype(x.dtype), (((2,), (0,)), ((), ())))   # (B, T, 3C)
    out, lse = _fwd_packed(qkv, qkv, qkv, H, D, scale=scale,
                           causal=causal, block_q=block_q,
                           block_k=block_k, interpret=interpret,
                           seq_len=seq_len, head_base=(0, H, 2 * H))
    # qkv is NOT saved: the backward recomputes it from (x, w) — one
    # extra (B*T, C) @ (C, 3C) matmul in exchange for never holding the
    # (B, T, 3C) projection as a residual (201 MB/layer at the bench
    # shape; the dropped ~2.4 GB is what keeps XLA's auto-remat from
    # re-deriving a convolution per layer, docs/benchmarks.md).
    return out, (x, w, out, lse)


def _flash_qkv_proj_bwd(H, scale, causal, block_q, block_k, bwd_block_q,
                        bwd_block_k, interpret, seq_len, res, do):
    x, w, o, lse = res
    D = w.shape[1] // (3 * H)
    wc = w.astype(x.dtype)
    qkv = jax.lax.dot_general(x, wc, (((2,), (0,)), ((), ())))
    dq, dk, dv = _bwd_pallas_packed(
        qkv, qkv, qkv, o, lse, do, H, D, scale=scale, causal=causal,
        block_q=bwd_block_q, block_k=bwd_block_k, interpret=interpret,
        seq_len=seq_len, head_base=(0, H, 2 * H))
    dqkv = jnp.concatenate([dq, dk, dv], axis=-1)          # (B, T, 3C)
    dx = jax.lax.dot_general(
        dqkv, wc, (((2,), (1,)), ((), ()))).astype(x.dtype)
    dw = jax.lax.dot_general(
        x, dqkv, (((0, 1), (0, 1)), ((), ())),
        preferred_element_type=jnp.float32).astype(w.dtype)
    return dx, dw


_flash_qkv_proj.defvjp(_flash_qkv_proj_fwd, _flash_qkv_proj_bwd)


def flash_qkv_proj(x, w, num_heads: int, *, causal: bool = True,
                   scale: Optional[float] = None,
                   block_q: Optional[int] = None,
                   block_k: Optional[int] = None,
                   bwd_block_q: Optional[int] = None,
                   bwd_block_k: Optional[int] = None,
                   interpret: bool = False,
                   seq_len: Optional[int] = None):
    """Fused qkv-projection + flash attention: ``x @ w`` -> causal flash
    -> head-merged (B, T, C) output, with the projection RECOMPUTED in
    the backward instead of saved (see ``_flash_qkv_proj_fwd``).  ``w``
    is the (C, 3C) no-bias qkv kernel (q | k | v, head-major); matmuls
    run in ``x.dtype``.  Same lane-aligned-head constraint as
    :func:`flash_attention_qkv`."""
    B, T, _ = x.shape
    C3 = w.shape[1]
    if w.shape[0] != x.shape[2] or C3 % (3 * num_heads):
        raise ValueError(
            f"flash_qkv_proj: w must be (C, 3*num_heads*D), got "
            f"{w.shape} for x {x.shape}, num_heads={num_heads}")
    D = C3 // (3 * num_heads)
    if D % 128:
        raise ValueError(
            f"flash_qkv_proj needs lane-aligned heads (D % 128 == 0), "
            f"got D={D}")
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    block_q, block_k, bwd_block_q, bwd_block_k, seq_len = _resolve_blocks(
        T, "flash_qkv_proj", block_q, block_k, bwd_block_q, bwd_block_k,
        seq_len, "pad the sequence to a tileable length")
    return _flash_qkv_proj(x, w, int(num_heads), float(scale),
                           bool(causal), block_q, block_k,
                           bwd_block_q, bwd_block_k,
                           bool(interpret), seq_len)


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10, 11))
def _flash(q, k, v, scale, causal, block_q, block_k, bwd_block_q,
           bwd_block_k, interpret, bwd_impl, seq_len):
    out, _ = _fwd(q, k, v, scale=scale, causal=causal, block_q=block_q,
                  block_k=block_k, interpret=interpret, seq_len=seq_len)
    return out


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, bwd_block_q,
               bwd_block_k, interpret, bwd_impl, seq_len):
    out, lse = _fwd(q, k, v, scale=scale, causal=causal, block_q=block_q,
                    block_k=block_k, interpret=interpret, seq_len=seq_len)
    return out, (q, k, v, out, lse)


def _flash_bwd(scale, causal, block_q, block_k, bwd_block_q, bwd_block_k,
               interpret, bwd_impl, seq_len, res, do):
    q, k, v, o, lse = res
    if bwd_impl == "pallas":
        # The split pair is the measured default on v5e: its shorter
        # kernel bodies software-pipeline to ~96% MXU on their 7 block
        # matmuls, while the fused kernel's loop-carried dq scratch
        # (dynamic per-step slice) defeats Mosaic's cross-step overlap —
        # 5 matmuls at ~49% lost to 7 at ~96% (docs/benchmarks.md).
        bwd_impl = "pallas_split"
    if bwd_impl == "pallas_fused":
        # The fused kernel keeps a full-sequence f32 dq accumulator in
        # VMEM ((T, D) = nq*block_q*D floats); past the scratch budget it
        # would fail Mosaic allocation at compile time, so hand off to the
        # split two-kernel path instead (ring/Ulysses shard T across chips
        # long before this bound matters on one chip).
        T, D = q.shape[-2], q.shape[-1]
        if T * D * 4 <= _FUSED_DQ_SCRATCH_BYTES:
            return _bwd_pallas_fused(q, k, v, o, lse, do, scale=scale,
                                     causal=causal, block_q=bwd_block_q,
                                     block_k=bwd_block_k, interpret=interpret,
                                     seq_len=seq_len)
        bwd_impl = "pallas_split"
    if bwd_impl == "pallas_split":
        return _bwd_pallas(q, k, v, o, lse, do, scale=scale, causal=causal,
                           block_q=bwd_block_q, block_k=bwd_block_k,
                           interpret=interpret, seq_len=seq_len)
    return _bwd_xla(q, k, v, o, lse, do, scale=scale, causal=causal,
                    chunk=bwd_block_k, seq_len=seq_len)


_flash.defvjp(_flash_fwd, _flash_bwd)


def auto_block(T: int) -> int:
    """Largest TPU-tileable flash block for sequence length ``T``: ``T``
    itself when one multiple-of-8 block covers the array, else the
    largest lane-aligned (multiple-of-128) divisor of ``T`` up to 1024,
    falling back to the largest multiple-of-8 divisor (Mosaic requires
    blocks' sublane dim divisible by 8 — including a lone block; 128
    fills whole lanes, so when a choice exists the aligned block avoids
    padded-lane waste on the scores tile).  Bigger blocks amortize
    per-grid-step overhead: on v5e at T=2048 the 1024 block measured 2x
    faster forward and 1.4x faster grad than 256, and 1024x1024 is the
    largest square block whose f32 scores tile fits the 16 MB scoped
    VMEM (2048x1024 exceeds it; docs/benchmarks.md).  0 = cannot tile;
    :func:`flash_attention_auto` then pads."""
    if T <= 1024:
        return T if T % 8 == 0 else 0
    aligned = max((d for d in range(128, 1025, 128) if T % d == 0),
                  default=0)
    any8 = max((d for d in range(8, 1025, 8) if T % d == 0), default=0)
    # Alignment saves ~15% padded-lane waste; block size amortizes
    # per-step overhead (1024 measured 2x faster than 256).  Only take
    # the aligned divisor when it doesn't shrink the block by more than
    # 2x (e.g. T=2176: prefer 544 over the aligned 128).
    if aligned and aligned * 2 >= any8:
        return aligned
    return any8


def _resolve_blocks(T: int, fn_name: str, block_q, block_k, bwd_block_q,
                    bwd_block_k, seq_len, pad_hint: str):
    """Shared block defaulting + validation for the three entry points:
    auto-size missing blocks, clamp to T, enforce divide-T/multiple-of-8
    (Mosaic's sublane constraint) and the seq_len range.  Returns the
    four resolved blocks and the normalized seq_len."""
    if block_q is None or block_k is None:
        blk = auto_block(T)
        if blk == 0:
            raise ValueError(
                f"{fn_name}: sequence length {T} has no multiple-of-8 "
                f"block divisor; {pad_hint}")
        block_q = blk if block_q is None else block_q
        block_k = blk if block_k is None else block_k
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    # Backward blocks default to the forward blocks (see bwd_kv_block
    # for why not wider); explicit values obey the same constraints.
    bwd_block_q = block_q if bwd_block_q is None else min(bwd_block_q, T)
    bwd_block_k = block_k if bwd_block_k is None else min(bwd_block_k, T)
    for name, b in (("block_q", block_q), ("block_k", block_k),
                    ("bwd_block_q", bwd_block_q),
                    ("bwd_block_k", bwd_block_k)):
        if T % b or b % 8:
            raise ValueError(
                f"{fn_name}: {name}={b} must divide T={T} and be a "
                f"multiple of 8 (Mosaic sublane tiling); {pad_hint}")
    if seq_len is not None and not 0 < seq_len <= T:
        raise ValueError(f"{fn_name}: seq_len {seq_len} out of range "
                         f"for T={T}")
    if seq_len == T:
        seq_len = None
    return (int(block_q), int(block_k), int(bwd_block_q),
            int(bwd_block_k), seq_len)


def flash_attention_auto(q, k, v, *, causal: bool = True,
                         scale: Optional[float] = None):
    """:func:`flash_attention` with automatic block sizing and padding —
    the drop-in local attention kernel for models and for
    ``ulysses_attention(attn_fn=...)``.

    Block size from :func:`auto_block`.  Sequences that cannot tile (or
    would tile with a degenerate <64 block) are zero-padded to the next
    multiple of 256 (of 8 below 256); the kernel masks positions past the
    real length statically, so results and gradients are exact and no
    O(T^2) dense buffer ever materializes (VERDICT r2 weak #7 — the old
    dense fallback would OOM at exactly the lengths this kernel exists
    for).  Off-TPU the kernel runs in interpret mode so callers stay
    hermetic.
    """
    T = q.shape[1]
    interpret = jax.default_backend() != "tpu"
    blk = auto_block(T)
    if blk >= 64 or blk == T:
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               block_q=blk, block_k=blk,
                               interpret=interpret)
    unit = 256 if T > 256 else 8
    T_pad = -(-T // unit) * unit
    pad = [(0, 0), (0, T_pad - T), (0, 0), (0, 0)]
    blk = auto_block(T_pad)   # largest block that tiles the padded length
    out = flash_attention(
        jnp.pad(q, pad), jnp.pad(k, pad), jnp.pad(v, pad),
        causal=causal, scale=scale, block_q=blk,
        block_k=blk, interpret=interpret, seq_len=T)
    return out[:, :T]


def bwd_kv_block(T: int, block_q: int) -> int:
    """Widest backward KV block within the f32 scores-tile budget
    block_q*block_k <= 2^20 — a helper for EXPLICIT ``bwd_block_k``
    tuning only.  The default backward blocks equal the forward blocks:
    standalone the backward compiles up to 1024x2048, but inside a full
    transformer step that exceeds the 16 MB scoped VMEM (measured on
    v5e), and the wider blocks' win was within 3%."""
    budget = (1 << 20) // max(block_q, 1)
    return max((d for d in range(8, min(budget, T) + 1, 8) if T % d == 0),
               default=block_q)


def flash_attention(q, k, v, *, causal: bool = True,
                    scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    bwd_block_q: Optional[int] = None,
                    bwd_block_k: Optional[int] = None,
                    interpret: bool = False,
                    bwd_impl: str = "pallas",
                    seq_len: Optional[int] = None):
    """Fused flash attention for ``(B, T, H, D)`` inputs (same contract as
    :func:`~horovod_tpu.parallel.ring_attention.full_attention`).

    Block sizes default to :func:`auto_block` (the largest multiple-of-8
    divisor of ``T`` up to 1024 — the largest square block whose f32
    scores tile fits v5e's 16 MB scoped VMEM); explicit blocks must
    divide ``T`` and be multiples of 8 (Mosaic's sublane constraint).  Differentiable via the flash-backward identities
    (``bwd_impl="pallas"`` — VMEM-resident blockwise kernels; ``"xla"`` —
    the chunked-einsum fallback).  ``seq_len``: real length when the
    inputs are zero-padded to a tileable ``T`` — positions past it are
    masked statically in forward and backward.  Set ``interpret=True`` to
    run off-TPU (tests).
    """
    B, T, H, D = q.shape
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    if bwd_impl not in ("pallas", "pallas_fused", "pallas_split", "xla"):
        raise ValueError(f"bwd_impl must be 'pallas' (auto fused/split), "
                         f"'pallas_fused', 'pallas_split' or 'xla', got "
                         f"{bwd_impl!r}")
    block_q, block_k, bwd_block_q, bwd_block_k, seq_len = _resolve_blocks(
        T, "flash_attention", block_q, block_k, bwd_block_q, bwd_block_k,
        seq_len, "T divisible by the blocks is required — use "
        "flash_attention_auto (pads and masks) or full_attention for "
        "ragged lengths")

    # Head-packed path: lane-aligned head dims run the kernels directly
    # on (B, T, H*D) views via head-offset BlockSpecs — the reshape is
    # free (contiguous), so no transpose copy ever hits HBM.  Unaligned
    # D (or the opt-in fused/xla backwards) use the legacy merged layout.
    if D % 128 == 0 and bwd_impl in ("pallas", "pallas_split"):
        out = _flash_packed(
            q.reshape(B, T, H * D), k.reshape(B, T, H * D),
            v.reshape(B, T, H * D), int(H), float(scale), bool(causal),
            int(block_q), int(block_k), int(bwd_block_q),
            int(bwd_block_k), bool(interpret), seq_len)
        return out.reshape(B, T, H, D)

    def merge(x):   # (B, T, H, D) -> (B*H, T, D)
        return x.transpose(0, 2, 1, 3).reshape(B * H, T, D)

    out = _flash(merge(q), merge(k), merge(v), float(scale), bool(causal),
                 int(block_q), int(block_k), int(bwd_block_q),
                 int(bwd_block_k), bool(interpret), bwd_impl, seq_len)
    return out.reshape(B, H, T, D).transpose(0, 2, 1, 3)


def flash_attention_qkv(qkv, num_heads: int, *, causal: bool = True,
                        scale: Optional[float] = None,
                        block_q: Optional[int] = None,
                        block_k: Optional[int] = None,
                        bwd_block_q: Optional[int] = None,
                        bwd_block_k: Optional[int] = None,
                        interpret: bool = False,
                        seq_len: Optional[int] = None):
    """Flash attention straight off a fused qkv projection.

    Takes the ``(B, T, 3*C)`` output of one ``Dense(3*C)`` (q | k | v
    concatenated, each head-major with head dim ``D = C // num_heads``)
    and returns the head-merged ``(B, T, C)`` attention output.  The
    kernels read q/k/v via head-offset BlockSpecs into the SAME array,
    so neither the qkv split nor any (B, T, H, D) transpose ever copies
    in HBM — at the bench shape those layout copies were ~25 ms/step
    (docs/benchmarks.md).  Requires lane-aligned heads (``D % 128 ==
    0``); use :func:`flash_attention` otherwise.  Backward is always the
    split Pallas pair; the qkv cotangent is one concatenate.
    """
    B, T, C3 = qkv.shape
    if C3 % (3 * num_heads):
        raise ValueError(
            f"flash_attention_qkv: last dim {C3} must be 3*num_heads*D, "
            f"got num_heads={num_heads}")
    D = C3 // (3 * num_heads)
    if D % 128:
        raise ValueError(
            f"flash_attention_qkv needs lane-aligned heads (D % 128 == "
            f"0), got D={D}; split the projection and use "
            f"flash_attention instead")
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    block_q, block_k, bwd_block_q, bwd_block_k, seq_len = _resolve_blocks(
        T, "flash_attention_qkv", block_q, block_k, bwd_block_q,
        bwd_block_k, seq_len, "pad, or split and use "
        "flash_attention_auto")
    return _flash_qkv(qkv, int(num_heads), float(scale), bool(causal),
                      block_q, block_k, bwd_block_q,
                      bwd_block_k, bool(interpret), seq_len)
