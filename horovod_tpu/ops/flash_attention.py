"""Pallas flash attention — the fused single-chip attention hot path.

The transformer family's attention math (`full_attention`) leaves XLA to
materialize the (T, T) logits in HBM.  This kernel computes the same
causal softmax-attention with the flash schedule instead: Q blocks stay
resident in VMEM while K/V blocks stream through, the online-softmax
accumulators (running max / sum / output, all f32) never leave VMEM, and
the MXU sees back-to-back (block_q x d) @ (d x block_k) matmuls.  HBM
traffic drops from O(T^2) to O(T·d).

Layout: grid ``(batch*heads, T/block_q, T/block_k)`` with the KV axis
innermost ("arbitrary" semantics — accumulators persist across it);
causal Q/KV block pairs that are entirely masked are skipped with
``pl.when``, halving the work like the zigzag ring layout does across
chips.

Backward: ``jax.custom_vjp`` saving (o, logsumexp); gradients use the
standard flash-backward identities (dS = P * (dP - rowsum(dO*o))) with
blockwise XLA einsums over KV chunks via ``lax.map`` — linear memory, no
(T, T) materialization.

Composition: this is the *single-chip* block; for sequences sharded
across chips use :mod:`horovod_tpu.parallel.ring_attention`, which
streams K/V between chips with the same online-softmax math.

``interpret=True`` runs the kernel on CPU for tests; on TPU the shapes
must tile ((block sizes multiples of 128 ideally), else the caller should
fall back to ``full_attention``).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Shared with the oracle/ring implementations so masking stays numerically
# identical across all attention paths.
from horovod_tpu.parallel.ring_attention import _NEG_BIG, full_attention


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, causal, block_q, block_k):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_BIG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # Causal: a KV block strictly after the last query row of this Q block
    # contributes nothing — skip its compute entirely.
    q_last = (qi + 1) * block_q - 1
    k_first = kj * block_k

    @pl.when(jnp.logical_or(not causal, k_first <= q_last))
    def _compute():
        # Matmuls consume the native (bf16) element type so the MXU runs
        # at full rate; accumulation is f32 via preferred_element_type.
        q = q_ref[0]                                  # (BQ, D)
        k = k_ref[0]                                  # (BK, D)
        v = v_ref[0]                                  # (BK, D)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (BQ, BK)
        if causal:
            rows = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = kj * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(cols <= rows, s, _NEG_BIG)
        m_prev = m_scr[...]                            # (BQ, 128)
        block_max = jnp.max(s, axis=1, keepdims=True)  # (BQ, 1)
        m_new = jnp.maximum(m_prev, jnp.broadcast_to(block_max,
                                                     m_prev.shape))
        alpha = jnp.exp(m_prev[:, :1] - m_new[:, :1])  # (BQ, 1)
        p = jnp.exp(s - m_new[:, :1])                  # (BQ, BK)
        if causal:
            p = jnp.where(cols <= rows, p, 0.0)
        l_new = l_scr[...] * alpha + jnp.broadcast_to(
            jnp.sum(p, axis=1, keepdims=True), l_scr.shape)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = m_new
        l_scr[...] = l_new

    @pl.when(kj == nk - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0] = (acc_scr[...] / l).astype(o_ref.dtype)
        # lse laid out (BQ, 8) — the minimal last-dim tile the TPU block
        # constraints allow for this narrow per-row scalar.
        lse_ref[0] = jnp.broadcast_to(m_scr[:, :1] + jnp.log(l),
                                      (block_q, 8))


def _fwd(q, k, v, *, scale, causal, block_q, block_k, interpret):
    BH, T, D = q.shape
    nq = T // block_q
    nk = T // block_k
    grid = (BH, nq, nk)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 8), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, T, 8), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)
    return out, lse[..., 0]


def _bwd_xla(q, k, v, o, lse, do, *, scale, causal, chunk):
    """Flash backward with blockwise XLA einsums over KV chunks: linear
    memory, uses the saved logsumexp (no softmax recompute instability)."""
    BH, T, D = q.shape
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    delta = jnp.sum(dof * o.astype(jnp.float32), axis=-1)     # (BH, T)
    rows = jnp.arange(T)

    def one_chunk(dq_acc, start):
        ks = lax.dynamic_slice_in_dim(kf, start, chunk, axis=1)
        vs = lax.dynamic_slice_in_dim(vf, start, chunk, axis=1)
        cols = start + jnp.arange(chunk)
        s = jnp.einsum("btd,bcd->btc", qf, ks) * scale
        if causal:
            mask = cols[None, :] <= rows[:, None]             # (T, chunk)
            s = jnp.where(mask[None], s, _NEG_BIG)
        p = jnp.exp(s - lse[..., None])                       # (BH, T, c)
        if causal:
            p = jnp.where(mask[None], p, 0.0)
        dp = jnp.einsum("btd,bcd->btc", dof, vs)
        ds = p * (dp - delta[..., None]) * scale
        # dq accumulates across chunks in the scan carry (keeping per-chunk
        # dq stacked would be the O(T^2) buffer this path exists to avoid);
        # dk/dv tile the T axis, so stacking them is linear.
        dq_acc = dq_acc + jnp.einsum("btc,bcd->btd", ds, ks)
        dk_c = jnp.einsum("btc,btd->bcd", ds, qf)
        dv_c = jnp.einsum("btc,btd->bcd", p, dof)
        return dq_acc, (dk_c, dv_c)

    starts = jnp.arange(0, T, chunk)
    dq, (dk_chunks, dv_chunks) = lax.scan(
        one_chunk, jnp.zeros_like(qf), starts)
    dk = dk_chunks.transpose(1, 0, 2, 3).reshape(BH, T, D)
    dv = dv_chunks.transpose(1, 0, 2, 3).reshape(BH, T, D)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, scale, causal, block_q, block_k, interpret):
    out, _ = _fwd(q, k, v, scale=scale, causal=causal, block_q=block_q,
                  block_k=block_k, interpret=interpret)
    return out


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    out, lse = _fwd(q, k, v, scale=scale, causal=causal, block_q=block_q,
                    block_k=block_k, interpret=interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(scale, causal, block_q, block_k, interpret, res, do):
    q, k, v, o, lse = res
    return _bwd_xla(q, k, v, o, lse, do, scale=scale, causal=causal,
                    chunk=block_k)


_flash.defvjp(_flash_fwd, _flash_bwd)


def auto_block(T: int) -> int:
    """Largest TPU-tileable flash block for sequence length ``T``: ``T``
    itself when one block covers the array, else the largest
    multiple-of-8 divisor of ``T`` up to 128 (Mosaic requires interior
    blocks' sublane dim divisible by 8).  0 = cannot tile."""
    if T <= 128:
        return T
    return max((d for d in range(8, 129, 8) if T % d == 0), default=0)


def flash_attention_auto(q, k, v, *, causal: bool = True,
                         scale: Optional[float] = None):
    """:func:`flash_attention` with automatic block sizing and fallbacks —
    the drop-in local attention kernel for models and for
    ``ulysses_attention(attn_fn=...)``.

    Block size from :func:`auto_block`; sequences that cannot tile fall
    back to the dense path **with a warning** — the dense buffer is
    O(T^2), which at long-context lengths defeats the point of the
    kernel, so the caller should pad/trim to a tileable length.  Off-TPU
    the kernel runs in interpret mode so callers stay hermetic.
    """
    import warnings

    T = q.shape[1]
    blk = auto_block(T)
    if blk == 0:
        warnings.warn(
            f"flash_attention_auto: sequence length {T} has no "
            "multiple-of-8 block divisor <= 128; falling back to dense "
            "attention with an O(T^2) logits buffer. Pad or trim the "
            "sequence to a tileable length for the flash kernel.",
            RuntimeWarning, stacklevel=2)
        return full_attention(q, k, v, causal=causal, scale=scale)
    return flash_attention(q, k, v, causal=causal, scale=scale,
                           block_q=blk, block_k=blk,
                           interpret=jax.default_backend() != "tpu")


def flash_attention(q, k, v, *, causal: bool = True,
                    scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: bool = False):
    """Fused flash attention for ``(B, T, H, D)`` inputs (same contract as
    :func:`~horovod_tpu.parallel.ring_attention.full_attention`).

    Requires ``T % block == 0`` (clamps the blocks to ``T`` when the
    sequence is shorter); differentiable via the flash-backward identities.
    Set ``interpret=True`` to run off-TPU (tests).
    """
    B, T, H, D = q.shape
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    if T % block_q or T % block_k:
        raise ValueError(
            f"flash_attention needs T divisible by the block sizes, got "
            f"T={T}, block_q={block_q}, block_k={block_k}; use "
            f"full_attention for ragged lengths")

    def merge(x):   # (B, T, H, D) -> (B*H, T, D)
        return x.transpose(0, 2, 1, 3).reshape(B * H, T, D)

    out = _flash(merge(q), merge(k), merge(v), float(scale), bool(causal),
                 int(block_q), int(block_k), bool(interpret))
    return out.reshape(B, H, T, D).transpose(0, 2, 1, 3)
