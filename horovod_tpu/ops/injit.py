"""Collectives for use *inside* ``jit``/``shard_map`` — the static SPMD path.

The reference executes every collective through a dynamic negotiation
(enqueue → coordinator → MPI/NCCL call, ``horovod/common/operations.cc``).
Inside an XLA program none of that is needed: program order is identical on
every rank by construction, so a collective is just an op.  These wrappers
lower straight to XLA's AllReduce / AllGather / CollectivePermute over the
ICI mesh and exist to give the reference's op surface (names, averaging,
gradient semantics) a TPU-native home:

* ``allreduce``  ↔ ``MPI_Allreduce``/``ncclAllReduce`` paths
  (``operations.cc:1268-1281, 1179-1187``); gradient of allreduce is
  allreduce (reference ``horovod/tensorflow/mpi_ops.py:93-124``) — linearity
  gives JAX that for free.
* ``allgather``  ↔ ``MPI_Allgatherv`` (``operations.cc:796-856``); gradient
  is reduce-scatter = "allreduce then slice by rank offset"
  (``mpi_ops.py:126-164``), which is exactly the transpose XLA derives.
* ``broadcast``  ↔ ``MPI_Bcast`` (``operations.cc:1333-1353``); a real
  broadcast forward (binomial tree of CollectivePermutes — no AllReduce in
  the compiled program) whose ``custom_vjp`` backward is "psum the upstream
  grad, zeroed on non-root ranks" — the registered gradient at
  ``mpi_ops.py:167-182``.  ``mode="psum"`` selects the masked-psum
  formulation instead when a VMA-*invariant* (provably replicated) output
  is required.

All take ``axis_name`` (default ``'ranks'``, the world mesh axis) and work
under ``shard_map``/``pmap`` with that axis in scope.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.parallel.mesh import RANKS_AXIS

AxisName = Union[str, Sequence[str]]

# Reduction op names, mirroring hvd's average flag plus MPI-style ops.
SUM = "sum"
AVERAGE = "average"
MIN = "min"
MAX = "max"


def num_ranks(axis_name: AxisName = RANKS_AXIS):
    return lax.axis_size(axis_name)


def rank_index(axis_name: AxisName = RANKS_AXIS):
    return lax.axis_index(axis_name)


def allreduce(x, *, average: bool = True, op: Optional[str] = None,
              axis_name: AxisName = RANKS_AXIS):
    """Sum (or average/min/max) ``x`` across ranks; every rank gets the result.

    ``average=True`` matches the reference default where gradients are
    averaged rather than summed (``horovod/tensorflow/__init__.py:45-66``).
    """
    if op is None:
        op = AVERAGE if average else SUM
    if op == AVERAGE:
        return lax.pmean(x, axis_name)
    if op == SUM:
        return lax.psum(x, axis_name)
    if op == MIN:
        return lax.pmin(x, axis_name)
    if op == MAX:
        return lax.pmax(x, axis_name)
    raise ValueError(f"unknown reduction op: {op!r}")


def allgather(x, *, axis_name: AxisName = RANKS_AXIS, axis: int = 0):
    """Concatenate ``x`` from all ranks along ``axis`` (default 0), like the
    reference's allgather contract: same shape on all ranks except possibly
    dim0 (ragged dim0 is an eager-path feature; inside jit shapes are static
    and uniform)."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=True)


def _tree_broadcast(x, root_rank: int, axis_name: str):
    """Binomial-tree broadcast: ceil(log2 n) CollectivePermute rounds, the
    set of ranks holding root's value doubling each round.  No AllReduce
    appears in the program."""
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    rel = (idx - root_rank) % n
    cur = x
    step = 1
    while step < n:
        perm = [((root_rank + s) % n, (root_rank + s + step) % n)
                for s in range(step) if s + step < n]
        recv = lax.ppermute(cur, axis_name, perm)
        got = (rel >= step) & (rel < 2 * step)
        cur = jnp.where(got, recv, cur)
        step *= 2
    return cur


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _broadcast_permute(x, root_rank: int, axis_name: str):
    return _tree_broadcast(x, root_rank, axis_name)


def _broadcast_permute_fwd(x, root_rank, axis_name):
    return _tree_broadcast(x, root_rank, axis_name), None


def _broadcast_permute_bwd(root_rank, axis_name, _res, g):
    # The reference's registered gradient (mpi_ops.py:167-182): allreduce
    # the upstream grad; non-root ranks contribute zeros downstream.
    idx = lax.axis_index(axis_name)
    total = lax.psum(g, axis_name)
    return (jnp.where(idx == root_rank, total,
                      jnp.zeros_like(total)),)


_broadcast_permute.defvjp(_broadcast_permute_fwd, _broadcast_permute_bwd)


def broadcast(x, root_rank: int, *, axis_name: AxisName = RANKS_AXIS,
              mode: str = "permute"):
    """Every rank receives rank ``root_rank``'s value of ``x``.

    ``mode="permute"`` (default): a real broadcast — binomial tree of
    CollectivePermutes, no AllReduce in the forward program — with a
    ``custom_vjp`` reproducing the reference's registered gradient (psum
    of the cotangent, zeroed off-root, ``mpi_ops.py:167-182``).  Its
    output is VMA-**varying** (equal on every rank in fact, but the
    checker cannot see through a permute), so under
    ``shard_map(check_vma=True)`` return it through a per-rank
    ``out_spec`` (e.g. ``P('ranks')``) or keep consuming it in-scope.
    Code that returned the old masked-psum result through a REPLICATED
    ``out_spec`` (``P()``) will now fail at trace time with shard_map's
    varying-over-mesh-axes error — pass ``mode="psum"`` there to keep
    the provably-invariant formulation.

    ``mode="psum"``: the masked-psum formulation — ~2× the bytes on the
    forward but VMA-*invariant* output (usable with replicated
    ``out_specs``) and the same gradient via the autodiff transpose.
    Composite ``axis_name`` tuples always take this path (a tree over a
    product of axes would need a linearized permute).
    """
    if mode not in ("permute", "psum"):
        raise ValueError(f"broadcast mode must be 'permute' or 'psum', "
                         f"got {mode!r}")
    if mode == "psum" or not isinstance(axis_name, str):
        idx = lax.axis_index(axis_name)
        mask = (idx == root_rank).astype(x.dtype)
        return lax.psum(x * mask, axis_name)
    return _broadcast_permute(x, root_rank, axis_name)


def reducescatter(x, *, average: bool = False,
                  axis_name: AxisName = RANKS_AXIS, axis: int = 0):
    """Reduce across ranks and scatter equal chunks of ``axis`` to each rank.

    Not in the reference's public op set but it is the building block of its
    hierarchical allreduce (``ncclReduceScatter``, ``operations.cc:1090``);
    exposed because it is also the ZeRO-style primitive users expect.
    """
    out = lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)
    if average:
        out = out / lax.axis_size(axis_name)
    return out


def alltoall(x, *, axis_name: AxisName = RANKS_AXIS,
             split_axis: int = 0, concat_axis: int = 0):
    """All-to-all over the mesh axis (sequence/expert parallel building
    block; beyond the reference's three ops but first-class here)."""
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def staged_bucket_allreduce(leaves, reduce_flat, *, bucket_bytes=None,
                            overlap: bool = False):
    """Bucketed, staged collective over a list of flat (1-D) arrays.

    The in-jit half of the plane-agnostic scheduler: leaves are packed
    into byte-bounded buckets by :func:`horovod_tpu.scheduler
    .pack_buckets` (same packer as the eager overlap path — oversized
    leaves ride alone) and ``reduce_flat`` runs once per bucket on the
    concatenated payload, staged in the scheduler's issue order.  Under
    ``overlap`` that order is reversed registration order: backward
    materializes the LAST layer's gradients first, so emitting the tail
    bucket's collective first gives XLA's latency-hiding scheduler a
    collective whose inputs are ready while earlier layers are still
    differentiating.  Bucket contents do not depend on the issue order,
    so overlap changes scheduling, never math.

    Returns the reduced payload re-split per leaf (flat; caller
    reshapes).  ``reduce_flat`` must be shape-polymorphic over 1-D
    arrays (e.g. a quantized ring or a hierarchical allreduce).
    """
    from horovod_tpu import scheduler as _sched
    if bucket_bytes is None:
        bucket_bytes = _sched.bucket_bytes_from_env()
    sizes = [int(l.size) * int(l.dtype.itemsize) for l in leaves]
    dtypes = [str(l.dtype) for l in leaves]
    buckets = _sched.pack_buckets(sizes, dtypes, bucket_bytes)
    out = [None] * len(leaves)
    for b in _sched.issue_order(len(buckets), overlap):
        idxs = buckets[b]
        flat = (leaves[idxs[0]].ravel() if len(idxs) == 1
                else jnp.concatenate([leaves[i].ravel() for i in idxs]))
        red = reduce_flat(flat)
        offset = 0
        for i in idxs:
            n = leaves[i].size
            out[i] = red[offset:offset + n]
            offset += n
    return out
