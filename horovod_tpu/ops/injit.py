"""Collectives for use *inside* ``jit``/``shard_map`` — the static SPMD path.

The reference executes every collective through a dynamic negotiation
(enqueue → coordinator → MPI/NCCL call, ``horovod/common/operations.cc``).
Inside an XLA program none of that is needed: program order is identical on
every rank by construction, so a collective is just an op.  These wrappers
lower straight to XLA's AllReduce / AllGather / CollectivePermute over the
ICI mesh and exist to give the reference's op surface (names, averaging,
gradient semantics) a TPU-native home:

* ``allreduce``  ↔ ``MPI_Allreduce``/``ncclAllReduce`` paths
  (``operations.cc:1268-1281, 1179-1187``); gradient of allreduce is
  allreduce (reference ``horovod/tensorflow/mpi_ops.py:93-124``) — linearity
  gives JAX that for free.
* ``allgather``  ↔ ``MPI_Allgatherv`` (``operations.cc:796-856``); gradient
  is reduce-scatter = "allreduce then slice by rank offset"
  (``mpi_ops.py:126-164``), which is exactly the transpose XLA derives.
* ``broadcast``  ↔ ``MPI_Bcast`` (``operations.cc:1333-1353``); implemented
  as a masked psum so its JAX-derived gradient is "allreduce, zeroed on
  non-root ranks" — matching the registered gradient at
  ``mpi_ops.py:167-182``.

All take ``axis_name`` (default ``'ranks'``, the world mesh axis) and work
under ``shard_map``/``pmap`` with that axis in scope.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.parallel.mesh import RANKS_AXIS

AxisName = Union[str, Sequence[str]]

# Reduction op names, mirroring hvd's average flag plus MPI-style ops.
SUM = "sum"
AVERAGE = "average"
MIN = "min"
MAX = "max"


def num_ranks(axis_name: AxisName = RANKS_AXIS):
    return lax.axis_size(axis_name)


def rank_index(axis_name: AxisName = RANKS_AXIS):
    return lax.axis_index(axis_name)


def allreduce(x, *, average: bool = True, op: Optional[str] = None,
              axis_name: AxisName = RANKS_AXIS):
    """Sum (or average/min/max) ``x`` across ranks; every rank gets the result.

    ``average=True`` matches the reference default where gradients are
    averaged rather than summed (``horovod/tensorflow/__init__.py:45-66``).
    """
    if op is None:
        op = AVERAGE if average else SUM
    if op == AVERAGE:
        return lax.pmean(x, axis_name)
    if op == SUM:
        return lax.psum(x, axis_name)
    if op == MIN:
        return lax.pmin(x, axis_name)
    if op == MAX:
        return lax.pmax(x, axis_name)
    raise ValueError(f"unknown reduction op: {op!r}")


def allgather(x, *, axis_name: AxisName = RANKS_AXIS, axis: int = 0):
    """Concatenate ``x`` from all ranks along ``axis`` (default 0), like the
    reference's allgather contract: same shape on all ranks except possibly
    dim0 (ragged dim0 is an eager-path feature; inside jit shapes are static
    and uniform)."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=True)


def broadcast(x, root_rank: int, *, axis_name: AxisName = RANKS_AXIS):
    """Every rank receives rank ``root_rank``'s value of ``x``.

    Masked-psum formulation: its autodiff transpose is psum of the cotangent
    with non-root ranks zeroed — the exact registered gradient of the
    reference (``horovod/tensorflow/mpi_ops.py:167-182``).
    """
    idx = lax.axis_index(axis_name)
    mask = (idx == root_rank).astype(x.dtype)
    return lax.psum(x * mask, axis_name)


def reducescatter(x, *, average: bool = False,
                  axis_name: AxisName = RANKS_AXIS, axis: int = 0):
    """Reduce across ranks and scatter equal chunks of ``axis`` to each rank.

    Not in the reference's public op set but it is the building block of its
    hierarchical allreduce (``ncclReduceScatter``, ``operations.cc:1090``);
    exposed because it is also the ZeRO-style primitive users expect.
    """
    out = lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)
    if average:
        out = out / lax.axis_size(axis_name)
    return out


def alltoall(x, *, axis_name: AxisName = RANKS_AXIS,
             split_axis: int = 0, concat_axis: int = 0):
    """All-to-all over the mesh axis (sequence/expert parallel building
    block; beyond the reference's three ops but first-class here)."""
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)
