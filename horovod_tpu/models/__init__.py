"""Model zoo: TPU-first flax models used by the examples and benchmarks."""

from horovod_tpu.models.inception import InceptionV3, VGG16   # noqa: F401
from horovod_tpu.models.mlp import MLP, ConvNet          # noqa: F401
from horovod_tpu.models.resnet import (                   # noqa: F401
    ResNet, ResNet50, ResNet101, ResNet152,
)
from horovod_tpu.models.transformer import (               # noqa: F401
    BlockStack, TransformerLM)
