"""Inception V3 — the reference's headline scaling-benchmark model.

The reference's published 90%-at-512-GPU scaling number is measured on
Inception V3 (reference ``docs/benchmarks.md:3-6``, ``README.md:46-52``),
so the model belongs in the zoo alongside ResNet.  TPU-first like
:mod:`horovod_tpu.models.resnet`: NHWC layout, bf16 compute / f32 params
and batch-norm, static shapes, no Python control flow in the forward.

Standard V3 topology (Szegedy et al. 2015, the torchvision/keras layout):
stem (5 convs + 2 pools) → 3×A(35×35) → B → 4×C(17×17) → D → 2×E(8×8) →
global pool → dropout-free fc.  The aux classifier head is omitted — it
exists for a training schedule trick the benchmark never uses.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class ConvBN(nn.Module):
    """conv → BN → relu, the V3 building unit (bias-free conv)."""

    features: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: Any = "SAME"
    conv: ModuleDef = None
    norm: ModuleDef = None

    @nn.compact
    def __call__(self, x):
        x = self.conv(self.features, self.kernel, self.strides,
                      padding=self.padding)(x)
        x = self.norm()(x)
        return nn.relu(x)


def _pool(x, window=(3, 3), strides=(1, 1), kind="avg"):
    if kind == "avg":
        return nn.avg_pool(x, window, strides=strides, padding="SAME")
    return nn.max_pool(x, window, strides=strides, padding="VALID")


class InceptionA(nn.Module):
    pool_features: int
    cb: ModuleDef

    @nn.compact
    def __call__(self, x):
        b1 = self.cb(64, (1, 1))(x)
        b5 = self.cb(48, (1, 1))(x)
        b5 = self.cb(64, (5, 5))(b5)
        b3 = self.cb(64, (1, 1))(x)
        b3 = self.cb(96, (3, 3))(b3)
        b3 = self.cb(96, (3, 3))(b3)
        bp = _pool(x)
        bp = self.cb(self.pool_features, (1, 1))(bp)
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class InceptionB(nn.Module):
    """35×35 → 17×17 grid reduction."""

    cb: ModuleDef

    @nn.compact
    def __call__(self, x):
        b3 = self.cb(384, (3, 3), (2, 2), padding="VALID")(x)
        bd = self.cb(64, (1, 1))(x)
        bd = self.cb(96, (3, 3))(bd)
        bd = self.cb(96, (3, 3), (2, 2), padding="VALID")(bd)
        bp = _pool(x, strides=(2, 2), kind="max")
        return jnp.concatenate([b3, bd, bp], axis=-1)


class InceptionC(nn.Module):
    """Factorized 7×7 branches at 17×17."""

    channels_7x7: int
    cb: ModuleDef

    @nn.compact
    def __call__(self, x):
        c7 = self.channels_7x7
        b1 = self.cb(192, (1, 1))(x)
        b7 = self.cb(c7, (1, 1))(x)
        b7 = self.cb(c7, (1, 7))(b7)
        b7 = self.cb(192, (7, 1))(b7)
        bd = self.cb(c7, (1, 1))(x)
        bd = self.cb(c7, (7, 1))(bd)
        bd = self.cb(c7, (1, 7))(bd)
        bd = self.cb(c7, (7, 1))(bd)
        bd = self.cb(192, (1, 7))(bd)
        bp = _pool(x)
        bp = self.cb(192, (1, 1))(bp)
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class InceptionD(nn.Module):
    """17×17 → 8×8 grid reduction."""

    cb: ModuleDef

    @nn.compact
    def __call__(self, x):
        b3 = self.cb(192, (1, 1))(x)
        b3 = self.cb(320, (3, 3), (2, 2), padding="VALID")(b3)
        b7 = self.cb(192, (1, 1))(x)
        b7 = self.cb(192, (1, 7))(b7)
        b7 = self.cb(192, (7, 1))(b7)
        b7 = self.cb(192, (3, 3), (2, 2), padding="VALID")(b7)
        bp = _pool(x, strides=(2, 2), kind="max")
        return jnp.concatenate([b3, b7, bp], axis=-1)


class InceptionE(nn.Module):
    """Expanded 3×3 branches at 8×8."""

    cb: ModuleDef

    @nn.compact
    def __call__(self, x):
        b1 = self.cb(320, (1, 1))(x)
        b3 = self.cb(384, (1, 1))(x)
        b3 = jnp.concatenate([self.cb(384, (1, 3))(b3),
                              self.cb(384, (3, 1))(b3)], axis=-1)
        bd = self.cb(448, (1, 1))(x)
        bd = self.cb(384, (3, 3))(bd)
        bd = jnp.concatenate([self.cb(384, (1, 3))(bd),
                              self.cb(384, (3, 1))(bd)], axis=-1)
        bp = _pool(x)
        bp = self.cb(192, (1, 1))(bp)
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)


class InceptionV3(nn.Module):
    """Inception V3 for NHWC images (canonical input 299×299×3; any size
    ≥ 75 with both dims odd-reducible works thanks to SAME/VALID mix)."""

    num_classes: int = 1000
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       param_dtype=jnp.float32)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-3, dtype=self.dtype,
                       param_dtype=jnp.float32, axis_name=None)
        cb = partial(ConvBN, conv=conv, norm=norm)

        x = jnp.asarray(x, self.dtype)
        x = cb(32, (3, 3), (2, 2), padding="VALID")(x)
        x = cb(32, (3, 3), padding="VALID")(x)
        x = cb(64, (3, 3))(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = cb(80, (1, 1), padding="VALID")(x)
        x = cb(192, (3, 3), padding="VALID")(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))

        x = InceptionA(32, cb=cb)(x)
        x = InceptionA(64, cb=cb)(x)
        x = InceptionA(64, cb=cb)(x)
        x = InceptionB(cb=cb)(x)
        for c7 in (128, 160, 160, 192):
            x = InceptionC(c7, cb=cb)(x)
        x = InceptionD(cb=cb)(x)
        x = InceptionE(cb=cb)(x)
        x = InceptionE(cb=cb)(x)

        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        param_dtype=jnp.float32, name="head")(x)


class VGG16(nn.Module):
    """VGG-16 (the reference's 68%-scaling benchmark model,
    ``docs/benchmarks.md:3-6``): 13 convs in 5 stages + 3 fc.  BN-free
    like the original; f32 classifier head."""

    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    stage_sizes: Sequence[int] = (2, 2, 3, 3, 3)

    @nn.compact
    def __call__(self, x, train: bool = True):
        del train   # no train-time state; signature parity with the zoo
        x = jnp.asarray(x, self.dtype)
        features = 64
        for stage, n in enumerate(self.stage_sizes):
            for i in range(n):
                x = nn.Conv(min(features, 512), (3, 3), dtype=self.dtype,
                            param_dtype=jnp.float32,
                            name=f"conv{stage}_{i}")(x)
                x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
            features *= 2
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(4096, dtype=self.dtype,
                             param_dtype=jnp.float32, name="fc1")(x))
        x = nn.relu(nn.Dense(4096, dtype=self.dtype,
                             param_dtype=jnp.float32, name="fc2")(x))
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        param_dtype=jnp.float32, name="head")(x)
