"""Decoder-only transformer LM — the long-context model family.

Not in the reference (it predates transformers' dominance and is DP-only);
included because long-context sequence parallelism is first-class in this
framework.  TPU-first choices: bf16 compute / f32 params, static shapes,
pre-norm blocks, and a pluggable attention implementation:

* ``attn="full"``        — single-shard full attention (no SP),
* ``attn="flash"``       — single-shard Pallas flash attention
  (:mod:`horovod_tpu.ops.flash_attention`): same math, O(T·d) HBM traffic
  instead of the dense (T, T) buffer — 4-29x faster than the XLA dense
  path on v5e (docs/long-context.md),
* ``attn="ring"``        — :func:`horovod_tpu.parallel.ring_attention` (K/V
  ring over the mesh axis; sequence length scales with chips),
* ``attn="ring_zigzag"`` — ring attention with the load-balanced zigzag
  shard layout (tokens pre-permuted with
  :func:`~horovod_tpu.parallel.ring_attention.zigzag_indices`; ~2x faster
  causal hops),
* ``attn="ulysses"``     — :func:`horovod_tpu.parallel.ulysses` (all-to-all
  head/sequence re-shard),
* ``attn="ulysses_flash"`` — Ulysses with the Pallas flash kernel as the
  local attention (linear memory for the full-sequence local compute).

With ``attn != "full"`` the module must run inside shard_map with the
sequence dimension sharded on ``sp_axis``; position embeddings are computed
from the global position of each shard (rank offset, or the zigzag chunk
positions under ``ring_zigzag``).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.ops.flash_attention import (
    auto_block, flash_attention_auto, flash_qkv_proj)
from horovod_tpu.parallel.mesh import RANKS_AXIS
from horovod_tpu.parallel.ring_attention import (
    full_attention, ring_attention, zigzag_shard_positions)
from horovod_tpu.parallel.ulysses import ulysses_attention


class _QKVKernel(nn.Module):
    """Declares the same ``kernel`` param an ``nn.Dense(features,
    use_bias=False)`` would (name, shape, lecun-normal init) and returns
    it raw — used when the matmul itself lives inside a fused op, so the
    param tree stays interchangeable with the plain-Dense path."""
    features: int

    @nn.compact
    def __call__(self, in_features: int):
        return self.param("kernel", nn.initializers.lecun_normal(),
                          (in_features, self.features), jnp.float32)


class Attention(nn.Module):
    num_heads: int
    attn: str = "full"
    sp_axis: Any = RANKS_AXIS
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        B, T, C = x.shape
        D = C // self.num_heads
        blk = auto_block(T)
        if (self.attn == "flash" and D % 128 == 0
                and (blk == T or blk >= 64)):
            # Fused-projection fast path: one op computes qkv and runs
            # the kernels straight off it through head-offset BlockSpecs
            # — no split slice, no (B, T, H, D) transpose (measured ~25
            # ms/step of layout copies at the bench shape), and the
            # (B, T, 3C) projection is recomputed in the backward rather
            # than held as a residual (docs/benchmarks.md).
            w = _QKVKernel(3 * C, name="qkv")(C)
            out = flash_qkv_proj(
                x.astype(self.dtype), w, self.num_heads, causal=True,
                interpret=jax.default_backend() != "tpu")
            return nn.Dense(C, use_bias=False, dtype=self.dtype,
                            param_dtype=jnp.float32, name="proj")(out)
        qkv = nn.Dense(3 * C, use_bias=False, dtype=self.dtype,
                       param_dtype=jnp.float32, name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, self.num_heads, D)
        k = k.reshape(B, T, self.num_heads, D)
        v = v.reshape(B, T, self.num_heads, D)
        if self.attn == "ring":
            out = ring_attention(q, k, v, axis_name=self.sp_axis,
                                 causal=True)
        elif self.attn == "ring_zigzag":
            out = ring_attention(q, k, v, axis_name=self.sp_axis,
                                 causal=True, layout="zigzag")
        elif self.attn == "ulysses":
            out = ulysses_attention(q, k, v, axis_name=self.sp_axis,
                                    causal=True)
        elif self.attn == "full":
            out = full_attention(q, k, v, causal=True)
        elif self.attn == "flash":
            out = flash_attention_auto(q, k, v, causal=True)
        elif self.attn == "ulysses_flash":
            # Ulysses re-shard with the Pallas kernel as the local
            # attention — linear memory for the full-sequence local
            # compute instead of the dense (T, T) logits.
            out = ulysses_attention(q, k, v, axis_name=self.sp_axis,
                                    causal=True,
                                    attn_fn=flash_attention_auto)
        else:
            raise ValueError(f"unknown attention impl: {self.attn!r}")
        out = out.reshape(B, T, C)
        return nn.Dense(C, use_bias=False, dtype=self.dtype,
                        param_dtype=jnp.float32, name="proj")(out)


class Block(nn.Module):
    num_heads: int
    mlp_ratio: int = 4
    attn: str = "full"
    sp_axis: Any = RANKS_AXIS
    tp_axis: Any = None
    dtype: Any = jnp.bfloat16
    # LayerNorm compute dtype: f32 is the safe default; bf16 keeps the
    # residual stream out of f32 round-trips (~2x LN HBM traffic) at the
    # usual bf16-training precision trade (stats over d_model elements).
    ln_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        C = x.shape[-1]
        h = nn.LayerNorm(dtype=self.ln_dtype, name="ln1")(x)
        if self.tp_axis:
            # Megatron layout: heads and MLP hidden sharded over tp_axis,
            # one psum per sub-block (see parallel/tensor_parallel.py).
            from horovod_tpu.parallel.tensor_parallel import (
                TPMlp, TPSelfAttention)
            x = x + TPSelfAttention(self.num_heads, axis=self.tp_axis,
                                    dtype=self.dtype, name="attn")(h)
            h = nn.LayerNorm(dtype=self.ln_dtype, name="ln2")(x)
            return x + TPMlp(self.mlp_ratio * C, C, axis=self.tp_axis,
                             dtype=self.dtype, name="mlp")(h)
        x = x + Attention(self.num_heads, self.attn, self.sp_axis,
                          self.dtype, name="attn")(h)
        h = nn.LayerNorm(dtype=self.ln_dtype, name="ln2")(x)
        h = nn.Dense(self.mlp_ratio * C, dtype=self.dtype,
                     param_dtype=jnp.float32, name="fc1")(h)
        h = nn.gelu(h)
        h = nn.Dense(C, dtype=self.dtype, param_dtype=jnp.float32,
                     name="fc2")(h)
        return x + h


def _apply_block_stack(x, *, num_heads, depth, mlp_ratio, attn, sp_axis,
                       tp_axis, dtype, ln_dtype=jnp.float32):
    """Run ``depth`` Blocks named ``block_{i}`` in the caller's flax scope
    (shared by TransformerLM and BlockStack so their param trees agree)."""
    for i in range(depth):
        x = Block(num_heads, mlp_ratio=mlp_ratio, attn=attn,
                  sp_axis=sp_axis, tp_axis=tp_axis, dtype=dtype,
                  ln_dtype=ln_dtype, name=f"block_{i}")(x)
    return x


class BlockStack(nn.Module):
    """``depth`` consecutive transformer blocks — ONE pipeline stage.

    Activation-shape preserving, so it slots into
    :func:`horovod_tpu.parallel.pipeline.pipeline_apply` as ``stage_fn``:
    initialize per-stage params with ``stage_params_init``, keep the token
    embedding and LM head outside the pipeline (replicated), and each
    chip along ``pp`` runs its ``depth`` blocks.  See
    ``examples/jax_pipeline_transformer.py`` for the full wiring.
    """

    num_heads: int
    depth: int
    mlp_ratio: int = 4
    attn: str = "full"
    sp_axis: Any = RANKS_AXIS
    tp_axis: Any = None
    dtype: Any = jnp.bfloat16
    ln_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        return _apply_block_stack(
            x, num_heads=self.num_heads, depth=self.depth,
            mlp_ratio=self.mlp_ratio, attn=self.attn,
            sp_axis=self.sp_axis, tp_axis=self.tp_axis, dtype=self.dtype,
            ln_dtype=self.ln_dtype)


class TransformerLM(nn.Module):
    """Causal LM over token ids.

    Input: (B, T_local) int32 token ids — the full sequence when
    ``attn="full"``, this rank's shard otherwise.
    """
    vocab: int
    dim: int = 256
    depth: int = 4
    num_heads: int = 8
    max_len: int = 2048
    attn: str = "full"
    sp_axis: Any = RANKS_AXIS
    # Tensor parallelism: shard heads + MLP hidden over this mesh axis
    # (Megatron layout); embeddings/head replicated.  Requires running
    # inside shard_map with check_vma=True and attn="full".
    tp_axis: Any = None
    dtype: Any = jnp.bfloat16
    # LM-head matmul compute dtype.  f32 is the safe default; bf16 runs
    # the (T, d) @ (d, vocab) projection at full MXU rate (measured
    # ~20% of a d=2048/vocab=32k training step on v5e, docs/benchmarks.md)
    # — cast the logits back to f32 for the softmax in the loss.
    head_dtype: Any = jnp.float32
    # LayerNorm compute dtype (see Block.ln_dtype); bf16 for max MFU.
    ln_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, tokens, return_hidden=False):
        """``return_hidden=True`` skips the LM-head matmul and returns the
        final-LN hidden states — pair it with
        :func:`horovod_tpu.ops.losses.fused_softmax_xent` on
        ``params["head"]["kernel"]`` so the (T, vocab) logits are never
        materialized as autodiff residuals (init still uses the default
        call so the param tree always contains the head)."""
        if self.tp_axis and self.attn != "full":
            raise ValueError(
                "tp_axis composes with attn='full' only (TP attention "
                f"computes the full sequence locally); got {self.attn!r}")
        B, T = tokens.shape
        if self.attn in ("full", "flash"):
            pos = jnp.arange(T)
        elif self.attn == "ring_zigzag":
            pos = zigzag_shard_positions(
                lax.axis_index(self.sp_axis), lax.axis_size(self.sp_axis), T)
        else:
            pos = lax.axis_index(self.sp_axis) * T + jnp.arange(T)
        tok_emb = nn.Embed(self.vocab, self.dim, param_dtype=jnp.float32,
                           dtype=self.dtype, name="tok_emb")(tokens)
        pos_emb = nn.Embed(self.max_len, self.dim, param_dtype=jnp.float32,
                           dtype=self.dtype, name="pos_emb")(pos)
        x = tok_emb + pos_emb[None]
        x = _apply_block_stack(
            x, num_heads=self.num_heads, depth=self.depth, mlp_ratio=4,
            attn=self.attn, sp_axis=self.sp_axis, tp_axis=self.tp_axis,
            dtype=self.dtype, ln_dtype=self.ln_dtype)
        x = nn.LayerNorm(dtype=self.ln_dtype, name="ln_f")(x)
        if return_hidden:
            return x
        return nn.Dense(self.vocab, use_bias=False, dtype=self.head_dtype,
                        param_dtype=jnp.float32, name="head")(x)
