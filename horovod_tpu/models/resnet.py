"""ResNet family (v1.5) — the framework's flagship benchmark model.

The reference benchmarks ResNet-50/101 through tf_cnn_benchmarks and ships
``examples/keras_imagenet_resnet50.py`` / ``examples/pytorch_imagenet_resnet50.py``
(reference ``docs/benchmarks.md:8-39``).  This is a TPU-first re-design, not a
port of either:

* **NHWC layout** (channels-last) — the layout XLA:TPU expects; convolutions
  tile straight onto the MXU.
* **bfloat16 compute, float32 params** — matmul/conv FLOPs run in bf16 on the
  MXU; batch-norm statistics and the final logits stay in f32 for stability.
* Static shapes and no Python control flow in the forward pass, so the whole
  step compiles to one fused XLA program.

ResNet-50 = Bottleneck × [3, 4, 6, 3] (the standard v1.5 definition with the
stride-2 in the 3×3 conv, matching what keras.applications.ResNet50 gives the
reference example).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    """1×1 → 3×3 → 1×1 bottleneck with projection shortcut (ResNet v1.5:
    stride lives on the 3×3)."""

    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        # Zero-init the last BN scale: identity-at-init residual branches,
        # the standard large-batch trick (Goyal et al.) the reference's
        # warmup callback cites (horovod/keras/callbacks.py:114-134).
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    """ResNet v1.5 for NHWC images.

    ``remat=True`` checkpoints each bottleneck block: the backward pass
    recomputes block activations instead of streaming them from HBM —
    trading MXU FLOPs (abundant at this model's ~32% MFU) for HBM
    bandwidth (the measured bottleneck; see docs/benchmarks.md).
    """

    stage_sizes: Sequence[int]
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    remat: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       param_dtype=jnp.float32)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                       param_dtype=jnp.float32, axis_name=None)
        act = nn.relu
        block_cls = (nn.remat(BottleneckBlock) if self.remat
                     else BottleneckBlock)

        x = jnp.asarray(x, self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                 name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        block_idx = 0
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                # Explicit name: nn.remat changes the auto-derived module
                # path, which would make remat=True/False checkpoints
                # incompatible; pinning the name keeps one param tree.
                x = block_cls(self.num_filters * 2 ** i, strides,
                              conv=conv, norm=norm, act=act,
                              name=f"BottleneckBlock_{block_idx}")(x)
                block_idx += 1
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=jnp.float32, name="head")(x)
        return x


ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3])
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3])
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3])
