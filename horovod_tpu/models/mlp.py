"""MNIST-scale models — the framework's smoke-test model family.

The reference exercises its optimizer path with small MNIST networks
(``examples/tensorflow_mnist.py:39-68`` conv net,
``examples/pytorch_mnist.py:44-63``, ``examples/keras_mnist.py:41-54``).
These are their TPU-native counterparts: NHWC, static shapes, bf16-friendly.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    """Plain MLP classifier (for flattened inputs)."""

    features: Sequence[int] = (128, 64)
    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        for i, f in enumerate(self.features):
            x = nn.Dense(f, dtype=self.dtype, name=f"dense_{i}")(x)
            x = nn.relu(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)


class ConvNet(nn.Module):
    """The reference MNIST conv net shape: 2 convs + pool + 2 dense
    (reference ``examples/tensorflow_mnist.py:39-68``,
    ``examples/pytorch_mnist.py:44-63``), NHWC for the MXU."""

    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        if x.ndim == 3:
            x = x[..., None]
        x = x.astype(self.dtype)
        x = nn.Conv(32, (5, 5), padding="SAME", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (5, 5), padding="SAME", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(512, dtype=self.dtype)(x)
        x = nn.relu(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)
