"""Sparse gradient support — the reference's IndexedSlices path.

The reference allreduces a sparse gradient by **allgathering** its values
and indices instead of densifying (``horovod/tensorflow/__init__.py:67-78``),
exercised by ``examples/tensorflow_word2vec.py``.  Embedding-style gradients
touch few rows, so gathering the touched rows costs ``nnz × size`` instead
of a dense ``dim0`` allreduce.

TPU-native design: inside jit the gather is ``lax.all_gather`` over the
rank mesh (static shapes — every rank contributes the same number of rows,
the SPMD norm); eagerly it is the negotiated allgather, which supports
ragged row counts like ``MPI_Allgatherv``.  ``average=True`` divides values
by size, matching the reference's mean semantics; duplicate indices are
summed by the consumer (``apply_indexed_slices``), exactly like TF's
IndexedSlices contract.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from horovod_tpu.parallel.mesh import RANKS_AXIS


@dataclasses.dataclass
class IndexedSlices:
    """A sparse slice-set: ``dense[indices[i]] += values[i]`` semantics
    (mirrors ``tf.IndexedSlices``)."""
    values: jnp.ndarray          # (nnz, *row_shape)
    indices: jnp.ndarray         # (nnz,) int32/int64 rows into dim0
    dense_shape: Optional[Tuple[int, ...]] = None

    def to_dense(self):
        if self.dense_shape is None:
            raise ValueError("dense_shape required to densify")
        out = jnp.zeros(self.dense_shape,
                        jnp.result_type(self.values))
        return out.at[self.indices].add(self.values)


def allreduce(slices: IndexedSlices, *, average: bool = True,
              axis_name=RANKS_AXIS) -> IndexedSlices:
    """In-jit sparse allreduce: allgather rows + indices across ranks
    (reference ``horovod/tensorflow/__init__.py:67-78``).  Must run under
    shard_map/pmap with ``axis_name`` in scope."""
    values = lax.all_gather(slices.values, axis_name, axis=0, tiled=True)
    indices = lax.all_gather(slices.indices, axis_name, axis=0, tiled=True)
    if average:
        values = values / lax.axis_size(axis_name)
    return IndexedSlices(values, indices, slices.dense_shape)


def allreduce_eager(slices, *, average: bool = True,
                    name: Optional[str] = None) -> IndexedSlices:
    """Eager sparse allreduce via the negotiated allgather; per-rank row
    counts may differ (``MPI_Allgatherv`` parity)."""
    from horovod_tpu import basics
    from horovod_tpu.ops import eager

    nm = name or eager._auto_name("sparse.allreduce")
    if isinstance(slices, IndexedSlices):
        vals, idxs, dense_shape = (slices.values, slices.indices,
                                   slices.dense_shape)
        vh = eager.allgather_async(np.asarray(vals), name=f"{nm}.values")
        ih = eager.allgather_async(np.asarray(idxs), name=f"{nm}.indices")
    else:   # PerRank of IndexedSlices — distinct contributions per rank
        per = list(slices.values)
        dense_shape = per[0].dense_shape
        vh = eager.allgather_async(
            eager.PerRank([np.asarray(s.values) for s in per]),
            name=f"{nm}.values")
        ih = eager.allgather_async(
            eager.PerRank([np.asarray(s.indices) for s in per]),
            name=f"{nm}.indices")
    values = jnp.asarray(eager.synchronize(vh))
    indices = jnp.asarray(eager.synchronize(ih))
    if average:
        values = values / basics.size()
    return IndexedSlices(values, indices, dense_shape)


def apply_indexed_slices(dense, slices: IndexedSlices, *, scale=1.0):
    """``dense[indices] += scale * values`` with duplicate-index summation —
    the consumer side of a gathered sparse gradient (what TF's optimizers
    do with IndexedSlices)."""
    return dense.at[slices.indices].add(
        jnp.asarray(scale, dense.dtype) *
        slices.values.astype(dense.dtype))
