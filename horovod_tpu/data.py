"""Input pipeline utilities — the framework side of the data contract.

The reference delegates data loading to its host frameworks but its
examples all repeat the same moves: shard the dataset per rank
(``DistributedSampler`` / ``dataset.shard``, e.g.
``examples/pytorch_mnist.py:98-103``), feed each step, keep per-rank
batch counts equal so no rank stalls the collectives.  On TPU the same
contract plus two TPU-specific needs:

* on a multi-controller pod each process must contribute ONLY its local
  rows of the global batch (``jax.make_array_from_process_local_data``);
* the host work of producing batch k+1 (generation, augmentation,
  ``device_put`` staging) should overlap the device running step k —
  and with ``make_train_step(steps_per_call=k)`` batches must arrive
  stacked k-deep.

:class:`ShardedLoader` packages all of it: wrap any iterable of host
batches (pytrees with a common leading batch dim), get back an iterator
of mesh-sharded device arrays, prefetched ``prefetch`` batches ahead on
a background thread, optionally stacked for the multi-step scan.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Iterator, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shard_for_process(batch, mesh: Mesh, spec=None):
    """Turn this PROCESS's local rows into a global mesh-sharded array.

    Single-controller: a plain sharded ``device_put`` (the batch is the
    global batch).  Multi-controller: the batch is only this process's
    shard of the global batch (the pod input contract —
    ``docs/running.md``), assembled with
    ``jax.make_array_from_process_local_data``.

    Contract warning: on a pod every process must pass its OWN rows; if
    every process holds the identical GLOBAL batch instead, use
    :func:`horovod_tpu.jax.spmd.shard_batch` — mixing the two contracts
    silently duplicates rows into an inflated global batch.
    """
    if spec is None:
        spec = P(tuple(mesh.axis_names))
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() > 1:
        return jax.tree.map(
            lambda a: jax.make_array_from_process_local_data(
                sharding, np.asarray(a)), batch)
    return jax.tree.map(lambda a: jax.device_put(a, sharding), batch)


class ShardedLoader:
    """Prefetching, mesh-sharding batch iterator.

    ``it`` yields host batches (pytrees; every leaf shares the leading
    batch dimension of this process's shard).  Iterating the loader
    yields device-resident, mesh-sharded batches; staging runs on a
    daemon thread ``prefetch`` batches ahead so host-side batch prep
    overlaps device compute.

    ``steps_per_call=k`` groups k consecutive batches and stacks them on
    a new leading axis — the layout :func:`make_train_step` expects for
    its multi-step scan; a trailing group smaller than k is dropped
    (like the reference's equal-batch-count contract, a partial scan
    call would desynchronize ranks).
    """

    def __init__(self, it, mesh: Mesh, *, spec=None,
                 steps_per_call: int = 1, prefetch: int = 2):
        if steps_per_call < 1:
            raise ValueError(f"steps_per_call must be >= 1, got "
                             f"{steps_per_call}")
        if prefetch < 1:
            raise ValueError(f"prefetch must be >= 1, got {prefetch}")
        # A zero-arg factory supports multi-epoch re-iteration; a plain
        # iterable/generator is single-use (a silently-empty second epoch
        # would be a training bug, so it raises instead).
        self._factory = it if callable(it) else None
        self._it = None if callable(it) else it
        self._consumed = False
        self._mesh = mesh
        base = spec if spec is not None else P(tuple(mesh.axis_names))
        # The scan axis leads every leaf when stacking: shard the dims
        # after it (mirrors make_train_step's batch_spec transform).
        self._spec = P(*([None] + list(base))) if steps_per_call > 1 \
            else base
        self._k = steps_per_call
        self._prefetch = prefetch

    def _stage(self, batch):
        if self._k > 1:
            batch = jax.tree.map(
                lambda *xs: np.stack(xs), *batch)
        return shard_for_process(batch, self._mesh, self._spec)

    def __iter__(self) -> Iterator[Any]:
        if self._factory is not None:
            source = self._factory()
        else:
            if self._consumed:
                raise RuntimeError(
                    "ShardedLoader built from a plain iterable is "
                    "single-use (a generator would silently yield an "
                    "empty second epoch); pass a zero-arg factory for "
                    "multi-epoch iteration")
            self._consumed = True
            source = self._it
        q: "queue.Queue" = queue.Queue(maxsize=self._prefetch)
        stop = threading.Event()
        _END = object()

        def put(item) -> bool:
            # Bounded put that gives up when the consumer went away, so
            # an abandoned iteration can't wedge the producer thread
            # holding device-resident batches forever.
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            try:
                group = []
                for host_batch in source:
                    if stop.is_set():
                        return
                    if self._k == 1:
                        if not put(self._stage(host_batch)):
                            return
                        continue
                    group.append(host_batch)
                    if len(group) == self._k:
                        if not put(self._stage(tuple(group))):
                            return
                        group = []
                # trailing partial group dropped (see class docstring)
                put(_END)
            except BaseException as exc:   # noqa: BLE001 — re-raised below
                put(exc)

        thread = threading.Thread(target=produce, daemon=True,
                                  name="horovod_tpu-data-prefetch")
        thread.start()
        try:
            while True:
                item = q.get()
                if item is _END:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()


def epoch_batches(x, y, batch_size: int, *, rank: int, size: int,
                  seed: Optional[int] = None):
    """Per-rank epoch iterator over in-memory arrays — the
    ``DistributedSampler`` pattern (reference
    ``examples/pytorch_mnist.py:98-103``): optional epoch shuffle
    (identical permutation on every rank via ``seed``), rank-strided
    rows, equal batch counts everywhere (tail dropped).
    """
    n = x.shape[0]
    order = np.arange(n)
    if seed is not None:
        np.random.RandomState(seed).shuffle(order)
    mine = order[rank::size]
    # Batch count derived from the GLOBAL minimum (n // size), not this
    # rank's local row count: with n % size != 0 some ranks hold one row
    # more, and a locally-derived count would let them dispatch an extra
    # collective step nobody else joins (pod deadlock).
    per_rank = (n // size) // batch_size
    for b in range(per_rank):
        idx = mine[b * batch_size:(b + 1) * batch_size]
        yield x[idx], y[idx]
