"""Process-local adaptive-precision autopilot (PR 19).

The negotiated (eager) plane runs the real controller inside the
coordinator's :class:`~horovod_tpu.policy.FleetPolicy`: workers report
per-bucket error-feedback residual norms over the request wire
(``FLAG_PRECISION_EXT``) and rank 0 stamps the chosen wire dtype into the
negotiated Response, so every rank agrees by construction.

This module is the plumbing each *worker process* needs around that, plus
the in-jit mirror:

* ``note_residual(name, norm)`` — record a measured relative residual
  norm for a bucket.  It is queued for the next request frame's
  precision ext (``drain_reports``) AND fed to a local
  :class:`~horovod_tpu.policy.FleetPolicy` mirror so jit-only programs
  (no coordinator) can run the same ladder.
* ``wire_dtype_for(name)`` / ``plan_version`` — the local mirror's
  current decision and a counter that bumps on every level change, so
  the in-jit path knows when its compiled plan is stale and must
  retrace.

Determinism note for the in-jit mirror: residuals are computed from the
*allreduced* gradients, which are bit-identical on every process, and the
ladder is a pure function of the observed sequence — so independent
per-process mirrors stay in lockstep without any negotiation.  If a
caller feeds per-process-varying values the mirrors can diverge; the
negotiated plane does not have this caveat (rank 0 decides alone).

Armed by ``HOROVOD_TPU_PRECISION=auto`` (default ``static`` — everything
here becomes a cheap no-op and wire frames stay byte-identical to a
build without this module).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Tuple

from horovod_tpu import policy as _policy


class PrecisionAutopilot:
    """Thread-safe per-process wrapper over the precision ladder."""

    def __init__(self):
        self._lock = threading.Lock()
        self._policy = _policy.FleetPolicy()
        self._pending: Dict[str, float] = {}
        self._version = 0

    @property
    def enabled(self) -> bool:
        """True when ``HOROVOD_TPU_PRECISION=auto`` armed the ladder."""
        return self._policy.precision_auto()

    @property
    def plan_version(self) -> int:
        """Bumped on every ladder level change anywhere; the in-jit
        ``compression="auto"`` path retraces when this moves."""
        with self._lock:
            return self._version

    def note_residual(self, name: str, residual_norm: float) -> None:
        """Record one measured relative residual norm for bucket ``name``.

        Queued for the next request frame (``drain_reports``) and fed to
        the local ladder mirror.  No-op unless the autopilot is armed;
        negative values (no measurement) are ignored.
        """
        if not self.enabled or residual_norm < 0:
            return
        with self._lock:
            self._pending[name] = float(residual_norm)
            self._policy.observe_precision(name, float(residual_norm))
            if self._policy.take_precision_dirty():
                self._version += 1

    def note_bandwidth(self, min_leg_bps: float) -> None:
        """Feed the slowest observed leg bandwidth to the promotion gate
        (``HOROVOD_TPU_PRECISION_BW_BPS``)."""
        if not self.enabled:
            return
        with self._lock:
            self._policy.note_precision_bandwidth(min_leg_bps)

    def drain_reports(self) -> List[Tuple[str, float]]:
        """Take (and clear) the residual reports queued since the last
        drain, in name order — the payload for the request frame's
        precision ext."""
        with self._lock:
            items = sorted(self._pending.items())
            self._pending.clear()
            return items

    def wire_dtype_for(self, name: str) -> str:
        """The local mirror's current wire dtype for ``name``
        (""/"bf16"/"int8")."""
        with self._lock:
            return self._policy.precision_wire(name)

    def level_for(self, name: str) -> int:
        with self._lock:
            return self._policy.precision_level(name)

    def ewma_for(self, name: str) -> float:
        with self._lock:
            return self._policy.precision_ewma(name)

    @property
    def promotions(self) -> int:
        with self._lock:
            return self._policy.precision_promotions

    @property
    def demotions(self) -> int:
        with self._lock:
            return self._policy.precision_demotions


_singleton: PrecisionAutopilot | None = None
_singleton_lock = threading.Lock()


def get_autopilot() -> PrecisionAutopilot:
    """The process-wide autopilot (created on first use; env knobs are
    read at that moment)."""
    global _singleton
    with _singleton_lock:
        if _singleton is None:
            _singleton = PrecisionAutopilot()
        return _singleton


def reset_autopilot() -> None:
    """Drop the singleton so the next ``get_autopilot`` re-reads the env
    (test isolation)."""
    global _singleton
    with _singleton_lock:
        _singleton = None
