"""Topology resolution for the TPU pod — replaces mpirun/MPI_COMM_WORLD.

The reference (Horovod v0.15.1) derives rank/size from MPI launched by
``mpirun`` and discovers node-locality via ``MPI_Comm_split_type(SHARED)``
(reference ``horovod/common/operations.cc:1469-1532``).  On TPU the topology
is a property of the pod runtime itself: JAX already knows how many chips
exist, which process owns which chips, and how processes map onto hosts.

TPU-native rank model (SPMD, one rank per chip):

* ``size``        — total number of participating devices (chips) in the job.
* ``local_size``  — number of chips attached to this process.
* ``rank``        — global index of this process's first chip.  With one
                    process per host this is the conventional "am I the
                    checkpointing process" identity (rank 0 == coordinator),
                    mirroring Horovod's ``hvd.rank()`` usage.
* ``local_rank``  — this process's index among processes on the same host
                    (0 for the single-process-per-host norm on TPU).

A single Python process drives all of its local chips (single-controller or
multi-controller SPMD); collectives therefore reduce over *devices*, and the
control plane (negotiation) runs per *process* with process 0 as coordinator,
mirroring Horovod's rank-0 coordinator (``operations.cc:1665-1693``).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np


@dataclasses.dataclass(frozen=True)
class Topology:
    """Immutable snapshot of the job topology at init time."""

    devices: Tuple[jax.Device, ...]          # all participating devices, rank order
    local_devices: Tuple[jax.Device, ...]    # devices owned by this process
    process_index: int
    process_count: int
    # Multi-process eager mode (TCP control plane): the global rank space
    # spans processes whose devices this process cannot see; these override
    # the device-derived values.  -1 = derive from devices.
    size_override: int = -1
    rank_override: int = -1
    # Host grouping discovered at init via the control-plane hostname
    # exchange (the reference's MPI_Comm_split_type(SHARED) equivalent,
    # operations.cc:1499-1509).  -1 = not discovered.
    local_rank_override: int = -1

    @property
    def size(self) -> int:
        if self.size_override >= 0:
            return self.size_override
        return len(self.devices)

    @property
    def local_size(self) -> int:
        return len(self.local_devices)

    @property
    def rank(self) -> int:
        """Global rank of this process's first device."""
        if self.rank_override >= 0:
            return self.rank_override
        first = self.local_devices[0]
        for i, d in enumerate(self.devices):
            if d.id == first.id:
                return i
        raise RuntimeError("local device not found in global device list")

    @property
    def local_rank(self) -> int:
        """Index of this process among processes on the same host
        (reference ``horovod/common/__init__.py:103-117``; derived there
        from a shared-memory comm split, ``operations.cc:1499-1509``).

        Resolution order: explicit ``HOROVOD_TPU_LOCAL_RANK`` (launcher
        override) → host grouping discovered by the control-plane hostname
        exchange (multi-process mode) → 0 (single process per host, the
        TPU pod norm).
        """
        import os
        env = os.environ.get("HOROVOD_TPU_LOCAL_RANK")
        if env is not None:
            return int(env)
        if self.local_rank_override >= 0:
            return self.local_rank_override
        return 0

    @property
    def is_coordinator(self) -> bool:
        """True when this process currently holds the coordinator seat
        (process index 0).  The seat is positional, not a fixed process:
        after an elastic coordinator failover the successor is densely
        re-ranked INTO index 0 (docs/elasticity.md), so this stays
        correct across takeovers — consult the live topology rather than
        caching the launch-time answer."""
        return self.process_index == 0

    @property
    def local_rank_device_ids(self) -> Tuple[int, ...]:
        return tuple(d.id for d in self.local_devices)

    def device_rank(self, device: jax.Device) -> int:
        for i, d in enumerate(self.devices):
            if d.id == device.id:
                return i
        raise KeyError(f"device {device} not in topology")


def host_fingerprint(warn_truncation: bool = False) -> str:
    """Host-unique identity for grouping processes by physical host — the
    stand-in for the reference's ``MPI_Comm_split_type(SHARED)``
    (``operations.cc:1499-1509``).

    Hostname alone is ambiguous both ways: two hosts can collide on a
    64-byte truncated name, and containers on one host can carry distinct
    names while sharing the hardware.  The kernel boot id is unique per
    booted host and shared by every container on it, so when readable it
    IS the fingerprint (the hostname must not participate in the equality,
    or distinct-named co-located containers split into separate groups).

    ``warn_truncation``: set by callers that compare only the first 64
    bytes (the control-plane wire field); the hash-based jit-only path
    compares the full string and has no truncation risk.

    ``HOROVOD_TPU_HOST_FINGERPRINT`` (non-empty) overrides everything —
    the test seam for faking multi-host layouts on one machine (the native
    control plane honours the same variable, control.cc HostFingerprint);
    it also serves as an escape hatch where boot-id sharing lies about
    locality (e.g. VMs cloned from one image without re-seeding).
    """
    import socket
    import warnings
    forced = os.environ.get("HOROVOD_TPU_HOST_FINGERPRINT", "")
    if forced:
        return forced
    try:
        with open("/proc/sys/kernel/random/boot_id") as f:
            boot = f.read().strip()
    except OSError:
        boot = ""
    if boot:
        return boot
    name = socket.gethostname()
    if warn_truncation and len(name.encode()) > 64:
        warnings.warn(
            "horovod_tpu: hostname exceeds the 64-byte host-grouping field "
            "and /proc/sys/kernel/random/boot_id is unreadable; hosts "
            "sharing this 64-byte name prefix would be grouped as one host "
            "(wrong local_rank/local_size).", RuntimeWarning, stacklevel=2)
    return name


def derive_host_groups(
        fingerprints: Sequence[str],
) -> Tuple[Dict[str, List[int]], List[int]]:
    """Host grouping + leader election from per-process host fingerprints
    (index = process index).

    Returns ``(groups, leaders)``: ``groups`` maps each fingerprint to the
    ascending list of process indices on that host; ``leaders`` is the
    per-host leader — the lowest process index of each host — ordered
    ascending, which IS the inter-host ring order of the hierarchical
    allreduce (mirrors ControlPlane::EnsureHierarchy, cpp/htpu/control.cc;
    both sides must elect identically or the data plane deadlocks).
    """
    groups: Dict[str, List[int]] = {}
    for pidx, fp in enumerate(fingerprints):
        groups.setdefault(fp, []).append(pidx)
    leaders = [procs[0] for procs in groups.values()]
    leaders.sort()
    return groups, leaders


def _device_coords(d) -> Optional[Tuple[int, ...]]:
    c = getattr(d, "coords", None)
    if c is None:
        return None
    try:
        return tuple(int(v) for v in c)
    except (TypeError, ValueError):
        return None


def physical_device_order(devices: Sequence) -> list:
    """Order devices so consecutive entries are physical ICI neighbours —
    the device-level analogue of the reference's locality discovery
    (``operations.cc:1499-1532``: MPI splits by shared memory; here the
    split data is the chip's own ``slice_index``/``coords``).

    Grouping is by ``slice_index`` first (chips in one slice share ICI;
    crossing slices means DCN), then by owning process — a process's
    devices MUST stay rank-contiguous because the shared-runtime
    executor and the multi-process launcher both address a process's
    ranks as the block ``[rank, rank + local_size)`` — and within each
    process a boustrophedon ("snake") walk of the chip coordinates, so
    consecutive pairs differ by one torus hop —
    ``mesh_utils.create_device_mesh``-style ordering without its
    fixed-slice-shape table.  Process blocks follow their first chip's
    snake position, so cross-block seams sit between physically close
    chips even though seam pairs may not be strict neighbours.  Multiple
    cores on one chip stay adjacent.  Devices that expose no coordinates
    (CPU meshes, virtual devices) are returned in the given order
    unchanged.
    """
    devs = list(devices)
    coords = [_device_coords(d) for d in devs]
    if any(c is None for c in coords) or not devs:
        return devs
    ndim = len(coords[0])
    if any(len(c) != ndim for c in coords):
        return devs
    lo = [min(c[i] for c in coords) for i in range(ndim)]
    extent = [max(c[i] for c in coords) - lo[i] + 1 for i in range(ndim)]

    def snake_key(d):
        c = [a - b for a, b in zip(_device_coords(d), lo)]
        # Walk the highest dim outermost; flip each lower dim's direction
        # by the parity of the walk position in the dims above it, so the
        # path only ever steps to an adjacent chip.
        key = []
        parity = 0
        for i in reversed(range(ndim)):
            v = c[i] if parity % 2 == 0 else extent[i] - 1 - c[i]
            key.append(v)
            parity = parity * extent[i] + v
        key.append(getattr(d, "core_on_chip", 0))
        return tuple(key)

    def full_key(d):
        return (getattr(d, "slice_index", 0) or 0,) + snake_key(d)

    groups: dict = {}
    for d in devs:
        groups.setdefault(getattr(d, "process_index", 0), []).append(d)
    for g in groups.values():
        g.sort(key=full_key)
    ordered_groups = sorted(groups.values(), key=lambda g: full_key(g[0]))
    return [d for g in ordered_groups for d in g]


def slice_groups(devices: Sequence, ici_size: Optional[int] = None):
    """Partition devices into the ``(dcn, ici)`` grid by PHYSICAL
    membership: chips sharing a ``slice_index`` form an ici group (they
    share ICI links); distinct slices stack along dcn.  Fallbacks when the
    runtime exposes no slice structure: group by ``process_index`` (host
    locality), or by an explicit ``ici_size``.

    Returns a list of equal-length device lists (one per ici group); an
    uneven partition raises, mirroring the reference's homogeneity check
    (``operations.cc:1511-1525``).
    """
    devs = list(devices)
    n = len(devs)
    if ici_size is not None:
        if n % ici_size != 0:
            raise ValueError(
                f"total ranks {n} not divisible by ici group size "
                f"{ici_size}; hierarchical collectives need a homogeneous "
                "topology (reference operations.cc:1511-1525 makes the "
                "same check)")
        return [devs[i:i + ici_size] for i in range(0, n, ici_size)]
    for attr in ("slice_index", "process_index"):
        vals = [getattr(d, attr, None) for d in devs]
        if any(v is None for v in vals):
            continue
        if len(set(vals)) <= 1:
            if attr == "slice_index":
                # One slice: EVERY chip shares ICI regardless of which
                # host drives it — host grouping would put dcn tiers on
                # ICI links.
                return [devs]
            continue
        groups: dict = {}
        for d, v in zip(devs, vals):
            groups.setdefault(v, []).append(d)
        sizes = {len(g) for g in groups.values()}
        if len(sizes) != 1:
            raise ValueError(
                f"device {attr} groups are uneven "
                f"({sorted((v, len(g)) for v, g in groups.items())}); "
                "hierarchical collectives need a homogeneous topology "
                "(reference operations.cc:1511-1525 makes the same check)")
        return [groups[v] for v in sorted(groups)]
    return [devs]   # one group: a single slice/host owns every chip


def resolve(ranks: Optional[Sequence[int]] = None) -> Topology:
    """Resolve the job topology from the JAX runtime.

    ``ranks`` optionally restricts participation to a subset of global device
    ranks, mirroring ``hvd.init(comm=[0, 1, ...])``'s subset-communicator
    support (reference ``horovod/common/__init__.py:58-68``,
    ``operations.cc:1469-1483``).

    Multi-process eager mode: when ``HOROVOD_TPU_COORD_ADDR`` is set
    together with ``HOROVOD_TPU_PROCESS_COUNT`` > 1, the rank space spans
    several independent processes connected by the TCP control plane (the
    launcher provides the layout, replacing ``mpirun``'s env propagation,
    reference ``docs/running.md:20-33``):

    * ``HOROVOD_TPU_SIZE``          — total ranks in the job,
    * ``HOROVOD_TPU_RANK``          — this process's first global rank,
    * ``HOROVOD_TPU_PROCESS_INDEX`` / ``HOROVOD_TPU_PROCESS_COUNT``.
    """
    import os
    if (os.environ.get("HOROVOD_TPU_COORD_ADDR")
            and int(os.environ.get("HOROVOD_TPU_PROCESS_COUNT", "1")) > 1):
        if ranks is not None:
            raise ValueError(
                "rank subsets are not supported in multi-process mode")
        local = tuple(jax.local_devices())
        size = int(os.environ["HOROVOD_TPU_SIZE"])
        rank = int(os.environ["HOROVOD_TPU_RANK"])
        # The launcher computed the global rank space from its
        # --ranks-per-process; if this process actually owns a different
        # number of devices the rank space has gaps/overlaps and every
        # negotiation deadlocks with only a stall warning.  Fail fast
        # instead (round-1 advisor finding).
        expected_local = int(os.environ.get("HOROVOD_TPU_LOCAL_SIZE", "0"))
        if expected_local and expected_local != len(local):
            raise RuntimeError(
                f"horovod_tpu: launcher assigned {expected_local} ranks to "
                f"this process but jax.local_devices() reports {len(local)} "
                "devices; the global rank space would have gaps and all "
                "collectives would stall. Pass --ranks-per-process matching "
                "the per-process device count (or adjust JAX_PLATFORMS/"
                "XLA_FLAGS so each process sees the intended devices).")
        # A standby's env-derived identity is a placeholder that lives
        # ABOVE the live rank space (run.py hands spares process indices
        # past the worker range); the controller adopts the real seat at
        # admission, so only seated processes get the overflow check.
        standby = os.environ.get("HOROVOD_TPU_STANDBY", "") == "1"
        if rank + len(local) > size and not standby:
            raise RuntimeError(
                f"horovod_tpu: rank layout overflows the job: first rank "
                f"{rank} + {len(local)} local devices > size {size}.")
        local = tuple(physical_device_order(local))
        return Topology(
            devices=local,
            local_devices=local,
            process_index=int(os.environ["HOROVOD_TPU_PROCESS_INDEX"]),
            process_count=int(os.environ["HOROVOD_TPU_PROCESS_COUNT"]),
            size_override=size,
            rank_override=rank,
        )
    all_devices = tuple(jax.devices())
    if ranks is not None:
        ranks = list(ranks)
        if sorted(set(ranks)) != sorted(ranks):
            raise ValueError("duplicate ranks in subset")
        if any(r < 0 or r >= len(all_devices) for r in ranks):
            raise ValueError(
                f"rank subset {ranks} out of range for {len(all_devices)} devices")
        devices = tuple(all_devices[r] for r in ranks)
    else:
        devices = all_devices
    # Physical (slice/torus-aware) order becomes THE rank order: rank r ==
    # mesh position r everywhere, and consecutive ranks are ICI neighbours
    # (no-op where the runtime exposes no coordinates).  Subset indices
    # above refer to the runtime's enumeration, as documented.
    devices = tuple(physical_device_order(devices))
    local = tuple(d for d in devices if d.process_index == jax.process_index())
    if not local:
        raise RuntimeError(
            "this process owns no devices in the requested rank subset")
    return Topology(
        devices=devices,
        local_devices=local,
        process_index=jax.process_index(),
        process_count=jax.process_count(),
    )


def mesh_devices(topology: Topology, shape: Tuple[int, ...]) -> np.ndarray:
    """Reshape the rank-ordered device list into a mesh array."""
    n = int(np.prod(shape))
    if n != topology.size:
        raise ValueError(f"mesh shape {shape} does not cover {topology.size} devices")
    return np.asarray(topology.devices, dtype=object).reshape(shape)
