"""SPMD training-step builder — the in-jit hot path of the framework.

The reference's hot path is: backward pass fires per-gradient hooks →
``allreduce_async_`` → background negotiation → fused MPI/NCCL allreduce →
``optimizer.step()`` (SURVEY §3.2/3.3).  The TPU-native equivalent compiles
all of that into ONE XLA program: ``shard_map`` over the rank mesh, gradients
averaged with in-program collectives (fusion and latency-hiding done by XLA),
optimizer update fused into the same program, buffers donated so params
update in place in HBM.

Two mesh layouts are supported, mirroring the reference's flat vs.
hierarchical allreduce (``operations.cc:879-1029`` vs ``:1025-1177``):

* 1-D ``('ranks',)`` mesh → flat ``pmean`` (XLA AllReduce over ICI).
* 2-D ``('dcn', 'ici')`` mesh → :func:`hierarchical_allreduce`
  (reduce-scatter on ICI, allreduce shards over DCN, allgather on ICI).
"""

from __future__ import annotations

import functools
import os
import time
from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from horovod_tpu import scheduler as _sched
from horovod_tpu.compression import Compressor, NoneCompressor
from horovod_tpu.ops import injit as _injit
from horovod_tpu.ops import quantized_collectives as _qc
from horovod_tpu.parallel._vma import ensure_varying_tree
from horovod_tpu.parallel.hierarchical import hierarchical_allreduce
from horovod_tpu.parallel.mesh import DCN_AXIS, ICI_AXIS


def reduce_gradients(grads, axis_names: Tuple[str, ...], *,
                     average: bool = True,
                     compression: Compressor = NoneCompressor,
                     fuse: bool = True,
                     bucket_bytes=None,
                     overlap=None):
    """Cross-rank gradient reduction inside a shard_map body.

    Uses the hierarchical two-tier path when the mesh is ('dcn', 'ici'),
    else a flat psum/pmean.  ``compression`` casts to the wire dtype around
    the collective (reference ``Compression.fp16``).

    Fusion story (the in-jit analogue of the reference's fusion buffer,
    ``operations.cc:1807-1842``): on a FLAT mesh, one pmean/psum
    primitive binds per leaf and XLA's AllReduce-combiner pass batches
    the adjacent collectives itself — explicit concat staging would only
    add copies, so ``fuse`` is a no-op there.  On the hierarchical
    ('dcn', 'ici') mesh the three staged collectives per tensor defeat
    that combiner, so ``fuse=True`` concatenates each wire dtype's
    leaves into bounded flat buckets and runs the three-stage hierarchy
    once per bucket (one HBM copy each way buys far fewer DCN launches,
    the tier the hierarchy exists to spare).

    ``compression=Compression.int8`` on a FLAT mesh engages the in-jit
    quantized ring instead (:mod:`horovod_tpu.ops.quantized_collectives`):
    eligible bulk leaves move as int8 + per-block scales on every hop,
    while 1-D / under-floor leaves stay on the raw psum path.  The
    ``HOROVOD_TPU_INJIT_WIRE_DTYPE`` env knob fills in the wire dtype
    where the caller left the default.

    Bucketing on the staged paths goes through the plane-agnostic
    scheduler (:mod:`horovod_tpu.scheduler`): ``bucket_bytes`` defaults
    to the ``HOROVOD_TPU_BUCKET_BYTES`` knob and ``overlap`` (default:
    ``HOROVOD_TPU_OVERLAP``) stages bucket collectives in reverse
    registration order — the backward pass materializes the tail
    buckets' gradients first, so XLA can run their collectives while
    earlier layers are still differentiating.  Bucket contents are
    issue-order independent: overlap on/off is bit-identical.
    """
    compression = _qc.resolve_injit_compression(compression)
    bucket_bytes = _sched.bucket_bytes_from_env(bucket_bytes)
    overlap = _sched.overlap_enabled(overlap)
    if compression is _AUTO_FROZEN or _qc.is_auto(compression):
        return _reduce_auto(grads, axis_names, average=average)
    hierarchical = set(axis_names) == {DCN_AXIS, ICI_AXIS}
    if (_qc.is_int8(compression) and not hierarchical
            and len(axis_names) == 1):
        return _reduce_flat_int8(grads, axis_names[0], average=average,
                                 fuse=fuse, bucket_bytes=bucket_bytes,
                                 overlap=overlap)

    def leaf_comp(g):
        # Bucket policy holds on every path: under int8, leaves below
        # the floor (norms, biases) skip the lossy snap and stay raw.
        if _qc.is_int8(compression) and not _qc.int8_eligible(
                g.shape, g.dtype):
            return NoneCompressor
        return compression

    def one(g):
        c, ctx = leaf_comp(g).compress(g)
        if hierarchical:
            red = hierarchical_allreduce(c, average=average)
        elif average:
            red = lax.pmean(c, axis_names)
        else:
            red = lax.psum(c, axis_names)
        # ctx=None marks a pass-through leaf, so the shared decompress
        # is correct for both policy outcomes.
        return compression.decompress(red, ctx)

    if not fuse:
        return jax.tree.map(one, grads)

    leaves, treedef = jax.tree.flatten(grads)
    compressed = [leaf_comp(g).compress(g) for g in leaves]
    if hierarchical:
        # Bucketed like the reference's bounded fusion buffer
        # (HOROVOD_FUSION_THRESHOLD, 64 MB default): the concat staging
        # copy peaks at one bucket, not the full model.  Per wire dtype,
        # the scheduler's shared packer decides the buckets (oversized
        # leaves ride alone) and the staged helper orders their
        # three-tier collectives.
        groups: dict = {}
        for i, (c, _) in enumerate(compressed):
            groups.setdefault(jnp.dtype(c.dtype), []).append(i)
        out = [None] * len(leaves)
        for idx_list in groups.values():
            reduced = _injit.staged_bucket_allreduce(
                [compressed[i][0] for i in idx_list],
                lambda flat: hierarchical_allreduce(flat, average=average),
                bucket_bytes=bucket_bytes, overlap=overlap)
            for i, r in zip(idx_list, reduced):
                c, ctx = compressed[i]
                out[i] = compression.decompress(r.reshape(c.shape), ctx)
        return jax.tree.unflatten(treedef, out)
    # Flat mesh: per-leaf collectives; XLA's AllReduce combiner batches
    # them (an explicit concat here measured as a wash on v5e and would
    # add two full-gradient copies).
    wire = [c for c, _ in compressed]
    wire = lax.pmean(wire, axis_names) if average else lax.psum(
        wire, axis_names)
    return jax.tree.unflatten(treedef, [
        compression.decompress(r, ctx)
        for r, (_, ctx) in zip(wire, compressed)])


class _AutoPlanFrozen:
    """Internal marker: one frozen trace of the adaptive-precision
    autopilot's CURRENT per-leaf plan.  ``make_train_step`` passes it to
    its inner build so the recursive call does not re-enter the auto
    dispatch wrapper; ``resolve_injit_compression`` passes it through
    untouched (it is neither a string nor the default compressor)."""


_AUTO_FROZEN = _AutoPlanFrozen()


def _reduce_auto(grads, axis_names, *, average: bool):
    """Per-leaf reduction under the adaptive-precision autopilot
    (``compression="auto"``).

    Each leaf's wire dtype is read from the process-local mirror
    (:func:`horovod_tpu.precision.get_autopilot`) at TRACE time and
    baked into the compiled program — ``make_train_step`` retraces when
    the mirror's ``plan_version`` moves.  Reduction is per leaf (no
    concat staging): on the flat mesh XLA's AllReduce combiner batches
    adjacent same-dtype collectives itself, and int8 leaves ride the
    quantized ring individually.  Leaves are named by their tree path
    (``grads['layer']['w']``) — the bucket key the mirror's ladder and
    the ``precision.*`` metrics use on this plane.
    """
    import jax.tree_util as jtu
    from horovod_tpu import precision as _precision
    from horovod_tpu.compression import compressor_for_wire
    pilot = _precision.get_autopilot()
    hierarchical = set(axis_names) == {DCN_AXIS, ICI_AXIS}

    def one(path, g):
        comp = compressor_for_wire(
            pilot.wire_dtype_for(f"grads{jtu.keystr(path)}"))
        if (_qc.is_int8(comp) and not hierarchical
                and len(axis_names) == 1
                and _qc.int8_eligible(g.shape, g.dtype)):
            flat = g.ravel().astype(jnp.float32)
            red = _qc.quantized_ring_allreduce(flat, axis_names[0],
                                               average=average)
            return red.reshape(g.shape).astype(g.dtype)
        if _qc.is_int8(comp) and not _qc.int8_eligible(g.shape, g.dtype):
            comp = NoneCompressor
        c, ctx = comp.compress(g)
        if hierarchical:
            red = hierarchical_allreduce(c, average=average)
        elif average:
            red = lax.pmean(c, axis_names)
        else:
            red = lax.psum(c, axis_names)
        return comp.decompress(red, ctx)

    return jtu.tree_map_with_path(one, grads)


def _reduce_flat_int8(grads, axis: str, *, average: bool, fuse: bool,
                      bucket_bytes: int, overlap: bool = False):
    """Flat-mesh gradient reduction over the in-jit int8 ring.

    Eligible bulk leaves (>= 2-D, at or above the size floor —
    :func:`~horovod_tpu.ops.quantized_collectives.int8_eligible`) are
    concatenated into bounded fp32 buckets by the scheduler's shared
    packer and each bucket rides one
    :func:`~horovod_tpu.ops.quantized_collectives
    .quantized_ring_allreduce`, staged in scheduler issue order; the
    rest take one multi-operand raw pmean/psum.  Fusing here matters
    more than on the raw path: XLA's AllReduce combiner cannot batch
    the explicit ppermute schedule, so per-leaf rings would serialize
    their hops.
    """
    leaves, treedef = jax.tree.flatten(grads)
    ring_idx = [i for i, g in enumerate(leaves)
                if _qc.int8_eligible(g.shape, g.dtype)]
    rest_idx = [i for i in range(len(leaves)) if i not in set(ring_idx)]
    out = [None] * len(leaves)
    if rest_idx:
        rest = [leaves[i] for i in rest_idx]
        red = lax.pmean(rest, axis) if average else lax.psum(rest, axis)
        for i, r in zip(rest_idx, red):
            out[i] = r
    if ring_idx:
        ring_leaves = [leaves[i].ravel().astype(jnp.float32)
                       for i in ring_idx]
        reduced = _injit.staged_bucket_allreduce(
            ring_leaves,
            lambda flat: _qc.quantized_ring_allreduce(flat, axis,
                                                      average=average),
            bucket_bytes=bucket_bytes if fuse else 0,
            overlap=overlap)
        for i, r in zip(ring_idx, reduced):
            g = leaves[i]
            out[i] = r.reshape(g.shape).astype(g.dtype)
    return jax.tree.unflatten(treedef, out)


class _StepWatchdog:
    """Opt-in liveness bound for jit-only pod training (VERDICT r3 #8).

    In jit-only mode there is no negotiation layer to detect a dead
    peer: a process crashing MID-STEP leaves the survivors blocked
    inside an XLA collective with no error (the eager path's stall scan
    and peer-crash CollectiveError cannot see inside a compiled
    program).  ``HOROVOD_TPU_STEP_TIMEOUT_S=<seconds>`` arms this
    monitor: every dispatched step's loss output is watched on a daemon
    thread, and if it fails to become ready within the deadline the
    process prints a loud diagnostic and aborts with exit code 83 — the
    fail-fast behavior a pod orchestrator needs to restart the job from
    the last checkpoint (pair with ``checkpoint.load_model``).  Steps
    pipeline, so each queued output's clock starts when the watcher
    reaches it (serial dependency makes earlier completion ≈ this
    step's start).  Disabled (zero overhead beyond one env read) by
    default: aborting a healthy-but-slow job is worse than hanging a
    dead one unless the operator opted in.
    """

    EXIT_CODE = 83

    def __init__(self, timeout_s: float):
        import queue
        self.timeout_s = timeout_s
        self._queue: "queue.Queue" = queue.Queue()
        self._thread = None

    def _loop(self):
        import time as _time
        while True:
            out = self._queue.get()
            deadline = _time.monotonic() + self.timeout_s
            while not self._ready(out):
                if _time.monotonic() > deadline:
                    import sys as _sys
                    print(
                        f"horovod_tpu: step watchdog: a dispatched train "
                        f"step did not complete within "
                        f"HOROVOD_TPU_STEP_TIMEOUT_S={self.timeout_s:g}s "
                        f"— on a multi-host jit-only job this usually "
                        f"means a peer process died mid-step and the "
                        f"collective can never complete.  Aborting so "
                        f"the orchestrator can restart from the last "
                        f"checkpoint.", file=_sys.stderr, flush=True)
                    _sys.stderr.flush()
                    os._exit(self.EXIT_CODE)
                _time.sleep(0.2)

    @staticmethod
    def _ready(out):
        # A failed/deleted output counts as "done": an error will surface
        # to the training loop itself; the watchdog only exists for the
        # silent-hang case, and must never die on an exception (a dead
        # watcher thread would silently disarm the timeout for the rest
        # of the job while watch() keeps enqueueing).
        try:
            return out.is_ready()
        except Exception:   # noqa: BLE001 — see above
            return True

    def watch(self, out):
        import threading
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="horovod_tpu-step-watchdog")
            self._thread.start()
        self._queue.put(out)


class _GuardedStage:
    """Proxy over a ``jax.stages`` Traced/Lowered object whose terminal
    ``.compile()`` re-applies the dispatch-time wrapper (ordering guard /
    watchdog / timeline spans), so the AOT route —
    ``step.lower(...).compile()`` — keeps the same per-call contract as
    direct dispatch (ADVICE r4: bench.py's own AOT path bypassed the
    guard and the step watchdog)."""

    def __init__(self, inner, rewrap):
        self._inner = inner
        self._rewrap = rewrap

    def lower(self, *args, **kwargs):
        return _GuardedStage(self._inner.lower(*args, **kwargs), self._rewrap)

    def compile(self, *args, **kwargs):
        return self._rewrap(self._inner.compile(*args, **kwargs))

    def __getattr__(self, name):
        return getattr(self._inner, name)


class _GuardedExecutable:
    """Callable proxy over a compiled executable: each call runs through
    ``around``; everything else (``cost_analysis`` etc.) delegates."""

    def __init__(self, inner, around):
        self._inner = inner
        self._around = around

    def __call__(self, *args, **kwargs):
        return self._around(self._inner, args, kwargs)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def _wrap_with_stages(fn, around):
    """Build the dispatch wrapper for ``fn`` plus ``lower``/``trace``
    passthroughs that keep ``around`` attached through AOT compilation."""

    def wrapped(*args, **kwargs):
        return around(fn, args, kwargs)

    def rewrap(compiled):
        return _GuardedExecutable(compiled, around)

    for attr in ("lower", "trace"):
        if hasattr(fn, attr):
            def passthrough(*a, _m=getattr(fn, attr), **kw):
                return _GuardedStage(_m(*a, **kw), rewrap)
            setattr(wrapped, attr, passthrough)
    return wrapped


def _wire_metrics(fn, mesh, compression, steps_per_call: int):
    """Per-dispatch ``injit.bytes#wire_dtype=*`` counters (ISSUE 6): the
    bytes each train-step dispatch is estimated to move per rank, split
    by wire dtype, folded into the process metrics registry next to the
    eager plane's ``ring.*`` series.  The plan is a pure function of the
    params tree's shapes and the wire policy, so it is computed once at
    the first dispatch and replayed as a counter bump per call."""
    hierarchical = set(mesh.axis_names) == {DCN_AXIS, ICI_AXIS}
    plan_cell: list = []

    def around(target, args, kwargs):
        out = target(*args, **kwargs)
        if not plan_cell:
            plan_cell.append(_qc.estimate_wire_plan(
                args[0], mesh.size, compression,
                hierarchical=hierarchical))
        _qc.record_wire_plan(plan_cell[0], steps=steps_per_call)
        return out

    return _wrap_with_stages(fn, around)


def _wire_observe(fn, steps_per_call: int):
    """Observatory step decomposition for the in-jit path.  Dispatch is
    async — the host call returns before the device finishes — so the
    device-step wall time is the *inter-dispatch* delta: once the
    pipeline is primed, the host re-enters dispatch exactly once per
    executed call, and any time it spends blocked *inside* dispatch
    (donation back-pressure, the runtime throttling enqueue) is stall
    the device pipeline could not hide.  Compute is the remainder;
    in-jit collectives are compiled into the program, so hidden/exposed
    comm are not separable here and are reported as zero (the eager
    overlap path owns those series)."""
    from horovod_tpu import observe as _observe

    t_prev = [0.0]

    def around(target, args, kwargs):
        t_in = time.perf_counter()
        out = target(*args, **kwargs)
        if not _observe.enabled():
            t_prev[0] = 0.0
            return out
        t_out = time.perf_counter()
        stall_s = (t_out - t_in) / steps_per_call
        if t_prev[0] > 0.0:
            step_s = max(0.0, (t_out - t_prev[0]) / steps_per_call)
            _observe.note_step(step_s, max(0.0, step_s - stall_s),
                               0.0, 0.0, stall_s)
        t_prev[0] = t_out
        return out

    return _wrap_with_stages(fn, around)


def _ordering_guard(fn, what: str = "make_train_step"):
    """Enforce the shared-runtime async-eager ordering contract at every
    dispatch: launching this jitted collective program while ``*_async``
    eager collectives are outstanding on a shared multi-controller
    runtime could interleave program launches differently per process
    (see :func:`horovod_tpu.basics.check_mesh_async_ordering`).  One
    attribute check + counter read per step when a controller exists.
    AOT compilation through the returned wrapper's ``lower``/``trace``
    yields executables with the same guard."""
    from horovod_tpu import basics

    timeout_s = float(os.environ.get("HOROVOD_TPU_STEP_TIMEOUT_S", "0"))
    watchdog = _StepWatchdog(timeout_s) if timeout_s > 0 else None

    def around(target, args, kwargs):
        basics.check_mesh_async_ordering(what)
        out = target(*args, **kwargs)
        if watchdog is not None:
            # Watch the loss: other outputs are typically donated into
            # the next call; one executable's outputs become ready
            # together.
            watchdog.watch(out[-1] if isinstance(out, tuple) else out)
        return out

    return _wrap_with_stages(fn, around)


class _StepSpans:
    """In-jit hot-path spans for the Horovod-style timeline (SURVEY §7.4
    item 6): the negotiated path traces itself in the executor, but the
    jitted train step — the actual hot path — would otherwise be
    invisible next to those spans.  Per step two lanes are emitted:

    * ``DISPATCH`` — the host call into XLA (trace + cache hit + enqueue;
      async, returns before the device finishes);
    * ``EXECUTE``  — dispatch-return until the step's outputs are ready,
      stamped by a single watcher thread so the training loop never
      blocks on instrumentation.

    Active only when a timeline is configured (``HOROVOD_TPU_TIMELINE``,
    rank 0); otherwise the per-call cost is one attribute check.
    """

    _instances = 0

    def __init__(self, name: str):
        import queue
        import types
        # Unique lane per instance: two instrumented steps sharing a lane
        # would interleave their B/E pairs into garbage durations.
        n = _StepSpans._instances
        _StepSpans._instances += 1
        suffix = f"[{n}]" if n else ""
        self._dispatch = types.SimpleNamespace(name=f"{name}{suffix}/dispatch")
        self._execute = types.SimpleNamespace(name=f"{name}{suffix}/execute")
        self._queue: "queue.Queue" = queue.Queue()
        self._watcher = None

    @staticmethod
    def _timeline():
        from horovod_tpu import basics
        controller = basics._state.controller
        return controller.timeline if controller is not None else None

    def _watch_loop(self):
        # Both edges of EXECUTE are stamped here so B/E pairs stay
        # properly nested even though dispatches pipeline ahead: steps are
        # serially dependent, so "previous step done" ≈ "this one starts".
        while True:
            timeline, outputs = self._queue.get()
            if timeline is None:
                return
            timeline.activity_start_all([self._execute], "EXECUTE")
            try:
                jax.block_until_ready(outputs)
            except Exception:   # noqa: BLE001 — step error surfaces to caller
                pass
            timeline.activity_end_all([self._execute])

    def instrument(self, fn):
        import threading

        def around(target, args, kwargs):
            timeline = self._timeline()
            if timeline is None:
                return target(*args, **kwargs)
            timeline.activity_start_all([self._dispatch], "DISPATCH")
            try:
                out = target(*args, **kwargs)
            finally:
                # A raising step must not leave an unbalanced B event.
                timeline.activity_end_all([self._dispatch])
            if self._watcher is None:
                self._watcher = threading.Thread(
                    target=self._watch_loop, daemon=True,
                    name="horovod_tpu-step-timeline")
                self._watcher.start()
            # Wait on the LOSS only: the other outputs are typically fed
            # straight back into the next call and donated there — the
            # watcher racing that donation would see 'Array has been
            # deleted' and stamp EXECUTE at next-dispatch time instead of
            # completion.  Outputs of one executable become ready
            # together, so the loss suffices.
            watch = out[-1] if isinstance(out, tuple) else out
            self._queue.put((timeline, watch))
            return out

        return _wrap_with_stages(fn, around)


def make_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    average: bool = True,
    compression: Compressor = NoneCompressor,
    sync_aux_state: bool = True,
    donate: bool = True,
    batch_spec=None,
    steps_per_call: int = 1,
    fuse: bool = True,
    overlap=None,
):
    """Build a jitted data-parallel training step over ``mesh``.

    ``loss_fn(params, aux_state, batch) -> (loss, new_aux_state)`` where
    ``params`` is the differentiable pytree, ``aux_state`` carries
    non-differentiable model state (e.g. flax ``batch_stats``; pass ``{}``
    if none), and ``batch`` is the *global* batch.

    ``batch_spec`` controls how batch leaves shard over the mesh; the
    default splits the leading dimension across every mesh axis (pure data
    parallel).  Pass e.g. ``P("dp", "sp")`` for a 2-D data × sequence
    layout (batch dim on ``dp``, sequence dim on ``sp`` — the loss_fn's
    model must then use the matching ``sp_axis``).

    Returns ``step(params, aux_state, opt_state, batch) ->
    (params, aux_state, opt_state, loss)`` — one XLA program containing
    forward, backward, gradient allreduce, and the optimizer update (the
    whole of SURVEY §3.2's multi-thread hot path, statically scheduled).

    ``steps_per_call > 1`` runs that many optimizer steps per dispatch with
    a ``lax.scan``: every batch leaf gains a leading ``steps_per_call``
    axis, and the returned loss is the mean over the scanned steps.  Use
    this to amortize host dispatch latency (measured ~2.4 ms/step on a
    tunneled v5e — 5% of a ResNet-50 step) when the input pipeline can
    stage several batches at once.

    ``fuse`` forwards to :func:`reduce_gradients` (fused collectives);
    ``fuse=False`` reduces per leaf, e.g. to avoid the hierarchical
    path's bucket staging copies under extreme memory pressure.
    ``overlap`` (default: the ``HOROVOD_TPU_OVERLAP`` knob) stages
    bucket collectives in backward order so they interleave with the
    remaining backprop — see :func:`reduce_gradients`.

    ``compression="auto"`` (pair with ``HOROVOD_TPU_PRECISION=auto``)
    lets the adaptive-precision autopilot pick each leaf's wire dtype:
    the returned step rebuilds its compiled program whenever the
    autopilot's plan changes (one retrace per promote/demote).  AOT
    ``.lower()`` is unavailable in this mode.
    """
    if _qc.is_auto(compression):
        # Adaptive-precision autopilot: the per-leaf wire plan is read
        # from the process-local mirror at trace time, so the compiled
        # program goes stale when the ladder moves a bucket.  Wrap the
        # build in a dispatcher that rebuilds (one retrace) whenever the
        # mirror's plan_version changes — promote/demote between steps,
        # not within one.  AOT ``.lower()`` is not supported here: an
        # ahead-of-time program cannot follow the ladder.
        from horovod_tpu import precision as _precision
        cell = {"v": None, "step": None}

        def _rebuild(version):
            cell["v"] = version
            cell["step"] = make_train_step(
                loss_fn, optimizer, mesh, average=average,
                compression=_AUTO_FROZEN, sync_aux_state=sync_aux_state,
                donate=donate, batch_spec=batch_spec,
                steps_per_call=steps_per_call, fuse=fuse, overlap=overlap)

        def dispatch(params, aux_state, opt_state, batch):
            v = _precision.get_autopilot().plan_version
            if cell["step"] is None or cell["v"] != v:
                _rebuild(v)
            return cell["step"](params, aux_state, opt_state, batch)

        return dispatch
    axes = tuple(mesh.axis_names)
    compression = _qc.resolve_injit_compression(compression)
    overlap = _sched.overlap_enabled(overlap)
    if steps_per_call < 1:
        raise ValueError(f"steps_per_call must be >= 1, got "
                         f"{steps_per_call}")

    def scan_steps(one_step, params, aux_state, opt_state, batches):
        def body(carry, batch):
            params, aux_state, opt_state = carry
            params, aux_state, opt_state, loss = one_step(
                params, aux_state, opt_state, batch)
            return (params, aux_state, opt_state), loss

        (params, aux_state, opt_state), losses = lax.scan(
            body, (params, aux_state, opt_state), batches,
            length=steps_per_call)
        return params, aux_state, opt_state, losses.mean()

    def spmd_body(params, aux_state, opt_state, batch):
        # Differentiate w.r.t. a VMA-varying view of the params: the
        # cotangents are then the raw *per-shard* gradients, which the
        # explicit reduce below averages with the chosen algorithm and
        # wire compression.  (Differentiating the invariant params instead
        # would make jax insert its own transpose-psum, pre-summing the
        # gradients and bypassing both knobs.)
        params_v = ensure_varying_tree(params, axes)
        (loss, new_aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params_v, aux_state, batch)
        grads = reduce_gradients(grads, axes, average=average,
                                 compression=compression, fuse=fuse,
                                 overlap=overlap)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        new_aux = _sync_or_check_aux(new_aux, axes, sync_aux_state)
        loss = lax.pmean(loss, axes)
        return params, new_aux, opt_state, loss

    replicated = P()
    if batch_spec is None:
        batch_spec = P(axes)   # leading dim split over every mesh axis
    if steps_per_call > 1:
        body = functools.partial(scan_steps, spmd_body)
        # The scan axis leads every batch leaf; shard the dims after it.
        batch_spec = jax.tree.map(
            lambda s: P(*([None] + list(s))), batch_spec,
            is_leaf=lambda s: isinstance(s, P))
    else:
        body = spmd_body
    step = shard_map(
        body, mesh=mesh,
        in_specs=(replicated, replicated, replicated, batch_spec),
        out_specs=(replicated, replicated, replicated, replicated),
        check_vma=True,
    )
    donate_argnums = (0, 1, 2) if donate else ()
    spmd_step = _ordering_guard(
        jax.jit(step, donate_argnums=donate_argnums))
    if mesh.size > 1:
        spmd_step = _wire_metrics(spmd_step, mesh, compression,
                                  steps_per_call)
    spans = _StepSpans("train_step")
    wire_identity = (compression is NoneCompressor
                     or isinstance(compression, NoneCompressor))
    if mesh.size > 1 or not wire_identity:
        return spans.instrument(_wire_observe(spmd_step, steps_per_call))

    # Single-chip fast path: on a 1-device mesh every collective is the
    # identity, but the shard_map wrapper still costs ~2% wall-clock
    # (measured on v5e ResNet-50, docs/benchmarks.md).  Compile the body
    # as a plain jit program instead — unless loss_fn itself uses mesh
    # axis names (e.g. a model with sp_axis modules), detected at first
    # trace, in which case fall back to the shard_map program.
    def plain_one(params, aux_state, opt_state, batch):
        (loss, new_aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, aux_state, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, new_aux, opt_state, loss

    if steps_per_call > 1:
        plain_body = functools.partial(scan_steps, plain_one)
    else:
        plain_body = plain_one
    plain_step = _ordering_guard(
        jax.jit(plain_body, donate_argnums=donate_argnums))
    chosen = []

    def _resolve(args):
        if not chosen:
            # Run the SPMD program's trace-time diagnostics even when the
            # plain program will execute: sync_aux_state=False's
            # varying-aux guard (_sync_or_check_aux) must fire on one
            # chip exactly as it would on a pod — a model developed
            # single-chip should not ship an aux bug that only surfaces
            # at the first multi-chip trace.  Only that diagnostic
            # propagates; other trace failures (e.g. pallas_call outputs
            # lacking vma annotations under check_vma) are deferred to
            # the real trace of whichever program is actually chosen.
            try:
                jax.eval_shape(step, *args)
            except ValueError as exc:
                if "varies across mesh shards" in str(exc):
                    raise
            try:
                # Trace without executing or donating: axis-name use
                # inside loss_fn surfaces as a NameError, and e.g.
                # DistributedOptimizer's SPMD-context detection surfaces
                # as a TracerArrayConversionError (it falls back to its
                # eager path when no mesh axis is bound).  ANY plain-
                # trace failure routes to the shard_map program — a
                # genuine user bug reproduces there and surfaces with
                # its real traceback at the call.
                jax.eval_shape(plain_body, *args)
                chosen.append(plain_step)
            except Exception:   # noqa: BLE001 — see comment above
                chosen.append(spmd_step)
        return chosen[0]

    def dispatch(params, aux_state, opt_state, batch):
        args = (params, aux_state, opt_state, batch)
        return _resolve(args)(*args)

    dispatch.lower = lambda *args: _resolve(args).lower(*args)
    return spans.instrument(_wire_observe(dispatch, steps_per_call))


def _sync_or_check_aux(new_aux, axes, sync_aux_state: bool):
    """Make the returned aux state provably replicated.

    ``sync_aux_state=True``: cross-replica sync of running statistics
    (each shard saw a different micro-batch) — float leaves are averaged,
    non-float leaves (step counters etc., identical by construction) are
    unified with a max.  ``False``: leaves must already be invariant over
    the mesh (untouched pass-throughs of the input state); a varying leaf
    means the model actually updates it per-shard, which would silently
    diverge — raise at trace time instead.
    """
    import jax.tree_util as jtu

    if sync_aux_state:
        # One multi-operand collective per reduction kind (not one per
        # leaf): float running statistics are averaged, non-float leaves
        # (step counters etc.) unified with a max.
        leaves, treedef = jax.tree.flatten(new_aux)
        float_idx = [i for i, a in enumerate(leaves) if jnp.issubdtype(
            jnp.result_type(a), jnp.floating)]
        other_idx = [i for i in range(len(leaves)) if i not in float_idx]
        out = list(leaves)
        if float_idx:
            red = lax.pmean([leaves[i] for i in float_idx], axes)
            for i, r in zip(float_idx, red):
                out[i] = r
        if other_idx:
            red = lax.pmax([leaves[i] for i in other_idx], axes)
            for i, r in zip(other_idx, red):
                out[i] = r
        return jax.tree.unflatten(treedef, out)

    def check(path, a):
        if getattr(jax.typeof(a), "vma", frozenset()):
            raise ValueError(
                f"make_train_step(sync_aux_state=False): aux state leaf "
                f"'{jtu.keystr(path)}' varies across mesh shards (each "
                "shard computed a different value from its micro-batch). "
                "Pass sync_aux_state=True to average it across ranks, or "
                "reduce it inside loss_fn.")
        return a

    return jtu.tree_map_with_path(check, new_aux)


def make_eval_step(apply_fn: Callable, mesh: Mesh):
    """Jitted eval step: ``apply_fn(params, aux_state, batch) -> metrics``
    with the batch sharded and metrics averaged across ranks."""
    axes = tuple(mesh.axis_names)

    def spmd_body(params, aux_state, batch):
        metrics = apply_fn(params, aux_state, batch)
        return lax.pmean(metrics, axes)   # pmean maps over the pytree

    step = shard_map(
        spmd_body, mesh=mesh,
        in_specs=(P(), P(), P(axes)), out_specs=P(),
        check_vma=True,
    )
    return jax.jit(step)


def shard_batch(batch, mesh: Mesh):
    """Device-put a host batch with its leading dim sharded over all mesh
    axes (the input-pipeline side of the data-parallel contract).

    Contract: ``batch`` is the GLOBAL batch, identical on every process —
    ``device_put`` slices out each process's addressable shards, so this
    works unchanged on a multi-controller pod where all processes hold
    the same host value.  When each process instead holds only ITS OWN
    rows (the scalable pod input pipeline), use
    :func:`horovod_tpu.data.shard_for_process` — passing a global batch
    to that helper (or local rows to this one) silently corrupts the
    global batch composition."""
    spec = P(tuple(mesh.axis_names))
    return jax.tree.map(
        lambda x: jax.device_put(x, NamedSharding(mesh, spec)), batch)
