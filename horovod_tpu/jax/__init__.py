"""JAX-first framework surface — the TPU-native ``hvd.DistributedOptimizer``.

The reference wraps TF/torch optimizers so every gradient is allreduced
before the update (``horovod/tensorflow/__init__.py:135-225``,
``horovod/torch/__init__.py:42-135``).  The idiomatic JAX equivalent is an
:mod:`optax` ``GradientTransformation`` wrapper: gradients are averaged
across the ``ranks`` mesh axis inside the jitted update (compiling to one
fused XLA AllReduce over ICI — fusion for free, no 64 MB buffer memcpys),
with an eager fallback when called outside an SPMD context.

Also here, mirroring the reference's startup-sync utilities:
``broadcast_parameters`` (``horovod/torch/__init__.py:138-167``) and
``broadcast_optimizer_state`` (``:170-263``) for pytrees, and
``allreduce_`` / ``allgather`` / ``broadcast`` over pytrees.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

from horovod_tpu import basics
from horovod_tpu.compression import Compression, Compressor, NoneCompressor
from horovod_tpu.ops import eager as _eager
from horovod_tpu.parallel.mesh import RANKS_AXIS


def _as_leaf(leaf):
    """Keep array leaves as they are — device-committed ``jax.Array``s flow
    to the executor's device-resident path with no host round-trip
    (VERDICT r4 weak #1); only non-array leaves (python scalars, lists)
    become host numpy so ``Compressor.compress`` can ``.astype`` them."""
    return (leaf if isinstance(leaf, (jax.Array, np.ndarray))
            else np.asarray(leaf))


def _in_spmd_context(axis_name) -> bool:
    """True when ``axis_name`` is bound (we are under shard_map/pmap)."""
    try:
        lax.axis_size(axis_name)
        return True
    except (NameError, KeyError, TypeError):
        return False


def _is_sparse(leaf) -> bool:
    from horovod_tpu.sparse import IndexedSlices
    return isinstance(leaf, IndexedSlices)


def DistributedOptimizer(
    optimizer: optax.GradientTransformation,
    *,
    axis_name=RANKS_AXIS,
    average: bool = True,
    compression: Compressor = NoneCompressor,
    sparse_as_dense: bool = False,
) -> optax.GradientTransformation:
    """Wrap an optax optimizer so updates consume rank-averaged gradients.

    Inside jit/shard_map (``axis_name`` in scope) the average compiles to a
    single XLA AllReduce; outside, gradients take the eager negotiated path.
    ``compression`` casts to a narrow wire dtype around the reduction
    (reference ``DistributedOptimizer(compression=...)``).

    :class:`horovod_tpu.sparse.IndexedSlices` gradient leaves are routed
    through the sparse **allgather** path automatically (the reference's
    IndexedSlices handling, ``horovod/tensorflow/__init__.py:67-78``);
    ``sparse_as_dense=True`` densifies them before a regular allreduce
    instead (reference ``__init__.py:141,167-179``).  Either way the inner
    optax transform sees a dense gradient — the comm stays sparse, the
    scatter to dense happens locally after the gather (optax has no
    IndexedSlices apply the way TF optimizers do).
    """

    def init(params):
        return optimizer.init(params)

    def update(grads, state, params=None, **kw):
        grads = allreduce_gradients(grads, axis_name=axis_name,
                                    average=average, compression=compression,
                                    sparse_as_dense=sparse_as_dense)
        grads = jax.tree.map(
            lambda g: g.to_dense() if _is_sparse(g) else g, grads,
            is_leaf=_is_sparse)
        return optimizer.update(grads, state, params, **kw)

    return optax.GradientTransformation(init, update)


def allreduce_gradients(grads, *, axis_name=RANKS_AXIS, average: bool = True,
                        compression: Compressor = NoneCompressor,
                        name_prefix: str = "DistributedOptimizer.grads",
                        grads_hint: bool = True,
                        sparse_as_dense: bool = False):
    """Average a gradient pytree across ranks (the allreduce-before-step
    core of every reference DistributedOptimizer).

    ``grads_hint`` tells the SPMD path how to treat values that are
    *unvaried* over the mesh axes: gradients of replicated params arrive
    pre-summed (jax.grad inserted the psum), so the allreduce-sum is the
    value itself; a generic replicated value (metric averaging via
    :func:`allreduce_`) instead has allreduce-sum = value × n.

    :class:`~horovod_tpu.sparse.IndexedSlices` leaves take the sparse
    allgather path and come back as gathered ``IndexedSlices`` (reference
    ``horovod/tensorflow/__init__.py:67-78``) — unless ``sparse_as_dense``
    densifies them up front.
    """
    from horovod_tpu import sparse as _sparse
    if sparse_as_dense:
        grads = jax.tree.map(
            lambda g: g.to_dense() if _is_sparse(g) else g, grads,
            is_leaf=_is_sparse)
    if _in_spmd_context(axis_name):
        axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)

        def one(g):
            if _is_sparse(g):
                return _sparse.allreduce(g, average=average,
                                         axis_name=axis_name)
            c, ctx = compression.compress(g)
            vma = getattr(jax.typeof(c), "vma", None)
            unvaried = vma is not None and not any(a in vma for a in axes)
            if unvaried and grads_hint:
                # Pre-summed gradient: dividing gives the mean; sum is c.
                red = c / lax.axis_size(axis_name) if average else c
            elif unvaried:
                # Replicated value: allreduce is identity (avg) or ×n (sum).
                red = c if average else c * lax.axis_size(axis_name)
            else:
                red = (lax.pmean(c, axis_name) if average
                       else lax.psum(c, axis_name))
            return compression.decompress(red, ctx)
        return jax.tree.map(one, grads, is_leaf=_is_sparse)
    # Eager path: compression is applied per-leaf around the negotiated op.
    leaves, treedef = jax.tree.flatten(grads, is_leaf=_is_sparse)
    flat_arrays = [a for l in leaves
                   for a in ((l.values, l.indices) if _is_sparse(l) else (l,))]
    if any(isinstance(l, jax.core.Tracer) for l in flat_arrays):
        axis = axis_name if isinstance(axis_name, str) else tuple(axis_name)
        raise RuntimeError(
            f"DistributedOptimizer/allreduce_gradients was traced inside "
            f"jit without the mesh axis {axis!r} in scope: the eager "
            f"fallback cannot run on tracers.  Run the update step via "
            f"horovod_tpu.jax.spmd.make_train_step (or your own "
            f"jax.shard_map over hvd.ranks_mesh()), or use the in-jit "
            f"collectives in horovod_tpu.ops.injit inside a plain jit.")
    handles, ctxs = [], []
    for i, leaf in enumerate(leaves):
        if _is_sparse(leaf):
            # Sparse leaf: allgather values+indices (async pair so small
            # embedding grads still overlap with the dense handles).
            vh = _eager.allgather_async(_as_leaf(leaf.values),
                                        name=f"{name_prefix}.{i}.values")
            ih = _eager.allgather_async(_as_leaf(leaf.indices),
                                        name=f"{name_prefix}.{i}.indices")
            handles.append((vh, ih, leaf.dense_shape))
            ctxs.append(None)
            continue
        arr = _as_leaf(leaf)
        if jnp.result_type(arr) == jnp.float32:
            # float32 leaves keep their dtype and compress ON THE WIRE of
            # the cross-process ring instead (full-precision accumulate,
            # compressed transfer; also how HOROVOD_TPU_WIRE_DTYPE and
            # Compression.int8 take effect on the eager path).
            ctxs.append(None)
            handles.append(_eager.allreduce_async(
                arr, average=average, name=f"{name_prefix}.{i}",
                compression=compression))
            continue
        c, ctx = compression.compress(arr)
        ctxs.append(ctx)
        handles.append(_eager.allreduce_async(
            c, average=average, name=f"{name_prefix}.{i}"))
    outs = []
    for h, ctx in zip(handles, ctxs):
        if isinstance(h, tuple):
            vh, ih, dense_shape = h
            values = jnp.asarray(_eager.synchronize(vh))
            if average:
                values = values / basics.size()
            outs.append(_sparse.IndexedSlices(
                values, jnp.asarray(_eager.synchronize(ih)), dense_shape))
        else:
            outs.append(compression.decompress(
                jnp.asarray(_eager.synchronize(h)), ctx))
    return jax.tree.unflatten(treedef, outs)


def broadcast_parameters(params, root_rank: int = 0,
                         name_prefix: str = "broadcast.params"):
    """Broadcast a parameter pytree from ``root_rank`` to all ranks —
    startup state sync (reference ``horovod/torch/__init__.py:138-167``,
    ``BroadcastGlobalVariablesHook``)."""
    leaves, treedef = jax.tree.flatten(params)
    handles = [
        _eager.broadcast_async(_as_leaf(leaf), root_rank,
                               name=f"{name_prefix}.{i}")
        for i, leaf in enumerate(leaves)]
    outs = []
    for leaf, h in zip(leaves, handles):
        out = _eager.synchronize(h)
        out = jnp.asarray(out, dtype=jnp.result_type(leaf))
        outs.append(out)
    return jax.tree.unflatten(treedef, outs)


def broadcast_optimizer_state(opt_state, root_rank: int = 0,
                              name_prefix: str = "broadcast.opt"):
    """Broadcast optimizer state from ``root_rank``.

    The reference walks torch's state_dict, wrapping python scalars as
    tensors and restoring their types after the broadcast
    (``horovod/torch/__init__.py:170-263``).  An optax state is already a
    pytree; python-int leaves (e.g. step counters) get the same
    wrap-as-array / restore-type treatment.
    """
    leaves, treedef = jax.tree.flatten(opt_state)
    out_leaves = []
    for i, leaf in enumerate(leaves):
        was_int = isinstance(leaf, int) and not isinstance(leaf, bool)
        was_float = isinstance(leaf, float)
        arr = _as_leaf(leaf)
        res = _eager.broadcast(arr, root_rank, name=f"{name_prefix}.{i}")
        if was_int:
            out_leaves.append(int(np.asarray(res)))
        elif was_float:
            out_leaves.append(float(np.asarray(res)))
        else:
            out_leaves.append(jnp.asarray(res, dtype=jnp.result_type(arr)))
    return jax.tree.unflatten(treedef, out_leaves)


def allreduce_(tree, *, average: bool = True, name_prefix: str = "allreduce"):
    """Allreduce of an arbitrary pytree (metric averaging etc.)."""
    return allreduce_gradients(tree, average=average,
                               name_prefix=name_prefix, grads_hint=False)


__all__ = [
    "DistributedOptimizer", "allreduce_gradients", "broadcast_parameters",
    "broadcast_optimizer_state", "allreduce_", "Compression",
]
