"""JAX-first framework surface — the TPU-native ``hvd.DistributedOptimizer``.

The reference wraps TF/torch optimizers so every gradient is allreduced
before the update (``horovod/tensorflow/__init__.py:135-225``,
``horovod/torch/__init__.py:42-135``).  The idiomatic JAX equivalent is an
:mod:`optax` ``GradientTransformation`` wrapper: gradients are averaged
across the ``ranks`` mesh axis inside the jitted update (compiling to one
fused XLA AllReduce over ICI — fusion for free, no 64 MB buffer memcpys),
with an eager fallback when called outside an SPMD context.

Also here, mirroring the reference's startup-sync utilities:
``broadcast_parameters`` (``horovod/torch/__init__.py:138-167``) and
``broadcast_optimizer_state`` (``:170-263``) for pytrees, and
``allreduce_`` / ``allgather`` / ``broadcast`` over pytrees.
"""

from __future__ import annotations

import time
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

from horovod_tpu import basics
from horovod_tpu import scheduler as _sched
from horovod_tpu.compression import Compression, Compressor, NoneCompressor
from horovod_tpu.metrics import registry as _metrics
from horovod_tpu.ops import eager as _eager
from horovod_tpu.ops import quantized_collectives as _qc
from horovod_tpu.parallel.mesh import RANKS_AXIS


def _as_leaf(leaf):
    """Keep array leaves as they are — device-committed ``jax.Array``s flow
    to the executor's device-resident path with no host round-trip
    (VERDICT r4 weak #1); only non-array leaves (python scalars, lists)
    become host numpy so ``Compressor.compress`` can ``.astype`` them."""
    return (leaf if isinstance(leaf, (jax.Array, np.ndarray))
            else np.asarray(leaf))


def _in_spmd_context(axis_name) -> bool:
    """True when ``axis_name`` is bound (we are under shard_map/pmap)."""
    try:
        lax.axis_size(axis_name)
        return True
    except (NameError, KeyError, TypeError):
        return False


def _is_sparse(leaf) -> bool:
    from horovod_tpu.sparse import IndexedSlices
    return isinstance(leaf, IndexedSlices)


class ErrorFeedbackState(NamedTuple):
    """Optimizer state of a ``DistributedOptimizer(error_feedback=True)``:
    the wrapped optimizer's state plus one fp32 residual per parameter
    leaf carrying the quantization error not yet applied."""
    inner: Any
    residual: Any


def DistributedOptimizer(
    optimizer: optax.GradientTransformation,
    *,
    axis_name=RANKS_AXIS,
    average: bool = True,
    compression: Compressor = NoneCompressor,
    sparse_as_dense: bool = False,
    error_feedback: bool = False,
    overlap: Optional[bool] = None,
) -> optax.GradientTransformation:
    """Wrap an optax optimizer so updates consume rank-averaged gradients.

    Inside jit/shard_map (``axis_name`` in scope) the average compiles to a
    single XLA AllReduce; outside, gradients take the eager negotiated path.
    ``compression`` casts to a narrow wire dtype around the reduction
    (reference ``DistributedOptimizer(compression=...)``).  With
    ``Compression.int8`` on the SPMD path, eligible bulk leaves ride the
    in-jit quantized ring (:mod:`horovod_tpu.ops.quantized_collectives`).

    ``error_feedback=True`` carries each leaf's quantization error as
    extra optimizer state (:class:`ErrorFeedbackState`) and adds it back
    into the next step's gradient before quantizing again (EQuARX /
    1-bit-SGD error feedback): components too small for this step's int8
    grid accumulate in the residual until they cross it, so convergence
    tracks the uncompressed run instead of flooring at the quantization
    noise.  Only meaningful with a lossy ``compression``; the residual
    is per-parameter fp32, so it costs one extra model copy of state.

    :class:`horovod_tpu.sparse.IndexedSlices` gradient leaves are routed
    through the sparse **allgather** path automatically (the reference's
    IndexedSlices handling, ``horovod/tensorflow/__init__.py:67-78``);
    ``sparse_as_dense=True`` densifies them before a regular allreduce
    instead (reference ``__init__.py:141,167-179``).  Either way the inner
    optax transform sees a dense gradient — the comm stays sparse, the
    scatter to dense happens locally after the gather (optax has no
    IndexedSlices apply the way TF optimizers do).

    ``overlap`` (default: the ``HOROVOD_TPU_OVERLAP`` knob) enables
    backward-overlap on the eager path: see
    :func:`allreduce_gradients`.

    ``compression="auto"`` hands the wire-dtype choice to the adaptive
    precision autopilot (``HOROVOD_TPU_PRECISION=auto``,
    :mod:`horovod_tpu.precision`): requests go out raw, measured residual
    norms ride the request wire to the coordinator, and the negotiated
    Response carries the per-bucket dtype every rank honors.  The
    ``error_feedback`` residual carry is a no-op under ``"auto"`` (the
    ladder demotes on residual spikes instead of carrying them).
    """

    def _residual_leaf(p):
        if jnp.issubdtype(jnp.result_type(p), jnp.floating):
            return jnp.zeros(jnp.shape(p), dtype=jnp.float32)
        return jnp.zeros((), dtype=jnp.float32)

    def init(params):
        inner = optimizer.init(params)
        if not error_feedback:
            return inner
        return ErrorFeedbackState(
            inner=inner,
            residual=jax.tree.map(_residual_leaf, params))

    def _lossy(comp, g):
        # Leaves the wire actually quantizes — the only ones whose
        # residual is non-trivial.  Matches the reduce-path policy.
        return (not _is_sparse(g) and _qc.is_int8(comp)
                and _qc.int8_eligible(jnp.shape(g), jnp.result_type(g)))

    def update(grads, state, params=None, **kw):
        inner_state = state.inner if error_feedback else state
        comp = _qc.resolve_injit_compression(compression)
        if error_feedback:
            def carry_in(g, r):
                if not _lossy(comp, g):
                    return g
                return g + r.astype(jnp.result_type(g))
            grads = jax.tree.map(carry_in, grads, state.residual,
                                 is_leaf=_is_sparse)
        red = allreduce_gradients(grads, axis_name=axis_name,
                                  average=average, compression=compression,
                                  sparse_as_dense=sparse_as_dense,
                                  overlap=overlap)
        if error_feedback:
            # Local-error formulation: what this rank contributed minus
            # what survived its own quantizer.  Q is deterministic and
            # shared with the wire (same block grid and scale rule), so
            # this is exactly the first-hop loss of the ring.
            def carry_out(g, r):
                if not _lossy(comp, g):
                    return r
                g32 = g.astype(jnp.float32)
                return g32 - _qc.snap_to_grid(g32)
            residual = jax.tree.map(carry_out, grads, state.residual,
                                    is_leaf=_is_sparse)
        red = jax.tree.map(
            lambda g: g.to_dense() if _is_sparse(g) else g, red,
            is_leaf=_is_sparse)
        updates, inner_state = optimizer.update(red, inner_state, params,
                                                **kw)
        if error_feedback:
            return updates, ErrorFeedbackState(inner=inner_state,
                                               residual=residual)
        return updates, inner_state

    return optax.GradientTransformation(init, update)


def allreduce_gradients(grads, *, axis_name=RANKS_AXIS, average: bool = True,
                        compression: Compressor = NoneCompressor,
                        name_prefix: str = "DistributedOptimizer.grads",
                        grads_hint: bool = True,
                        sparse_as_dense: bool = False,
                        overlap: Optional[bool] = None):
    """Average a gradient pytree across ranks (the allreduce-before-step
    core of every reference DistributedOptimizer).

    ``grads_hint`` tells the SPMD path how to treat values that are
    *unvaried* over the mesh axes: gradients of replicated params arrive
    pre-summed (jax.grad inserted the psum), so the allreduce-sum is the
    value itself; a generic replicated value (metric averaging via
    :func:`allreduce_`) instead has allreduce-sum = value × n.

    :class:`~horovod_tpu.sparse.IndexedSlices` leaves take the sparse
    allgather path and come back as gathered ``IndexedSlices`` (reference
    ``horovod/tensorflow/__init__.py:67-78``) — unless ``sparse_as_dense``
    densifies them up front.

    ``overlap`` (default: the ``HOROVOD_TPU_OVERLAP`` knob) switches the
    eager path to backward-overlap: float32 leaves are packed into
    scheduler buckets (``HOROVOD_TPU_BUCKET_BYTES``) and each bucket's
    fused allreduce is enqueued the moment its last gradient
    materializes on device, instead of after the whole tree is reduced
    leaf-by-leaf.  Payload packing is identical whether the bucket is
    issued early or late, so overlap changes timing, never math.

    ``compression="auto"`` engages the adaptive-precision autopilot: on
    the eager path requests are submitted raw (``wire_dtype=""``), the
    measured int8-grid residual norm of each reduced bucket is queued
    for the next request frame's precision ext, and the coordinator's
    negotiated Response decides the wire dtype; in SPMD context the
    process-local mirror (:func:`horovod_tpu.precision.get_autopilot`)
    supplies a per-leaf plan at trace time instead.
    """
    from horovod_tpu import sparse as _sparse
    if sparse_as_dense:
        grads = jax.tree.map(
            lambda g: g.to_dense() if _is_sparse(g) else g, grads,
            is_leaf=_is_sparse)
    # Canonicalize up front (string names -> Compressor, env default):
    # both the SPMD branch and the eager fallback below need a real
    # Compressor for the non-fp32 compress/decompress calls.
    compression = _qc.resolve_injit_compression(compression)
    auto = _qc.is_auto(compression)
    if auto:
        # Adaptive-precision autopilot: eager requests go out RAW
        # (wire_dtype="") and the negotiated Response carries the
        # coordinator's per-bucket choice; the SPMD branch reads the
        # process-local mirror per leaf at trace time instead.
        compression = NoneCompressor
    if _in_spmd_context(axis_name):
        axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)

        def one(g, comp):
            if _is_sparse(g):
                return _sparse.allreduce(g, average=average,
                                         axis_name=axis_name)
            vma_g = getattr(jax.typeof(g), "vma", None)
            varied = vma_g is None or any(a in vma_g for a in axes)
            if (varied and isinstance(axis_name, str) and _qc.is_int8(comp)
                    and _qc.int8_eligible(g.shape, g.dtype)):
                # Bulk leaf under int8: the in-jit quantized ring — int8
                # payload + per-block scales on every hop.  Under-floor
                # leaves fall through to the raw branch below (the
                # bucket policy; docs/concepts.md).
                return _qc.quantized_ring_allreduce(g, axis_name,
                                                    average=average)
            leaf_comp = (NoneCompressor if _qc.is_int8(comp)
                         else comp)
            c, ctx = leaf_comp.compress(g)
            vma = getattr(jax.typeof(c), "vma", None)
            unvaried = vma is not None and not any(a in vma for a in axes)
            if unvaried and grads_hint:
                # Pre-summed gradient: dividing gives the mean; sum is c.
                red = c / lax.axis_size(axis_name) if average else c
            elif unvaried:
                # Replicated value: allreduce is identity (avg) or ×n (sum).
                red = c if average else c * lax.axis_size(axis_name)
            else:
                red = (lax.pmean(c, axis_name) if average
                       else lax.psum(c, axis_name))
            return leaf_comp.decompress(red, ctx)
        if auto:
            # Per-leaf wire dtype from the autopilot mirror, read at
            # TRACE time (the compiled program bakes the plan in; the
            # caller retraces when the mirror's plan_version moves —
            # make_train_step(compression="auto") does this itself).
            import jax.tree_util as jtu
            from horovod_tpu import precision as _precision
            from horovod_tpu.compression import compressor_for_wire
            pilot = _precision.get_autopilot()
            return jtu.tree_map_with_path(
                lambda path, g: one(g, compressor_for_wire(
                    pilot.wire_dtype_for(
                        f"{name_prefix}{jtu.keystr(path)}"))),
                grads, is_leaf=_is_sparse)
        comp = compression
        return jax.tree.map(lambda g: one(g, comp), grads,
                            is_leaf=_is_sparse)
    # Eager path: compression is applied per-leaf around the negotiated op.
    leaves, treedef = jax.tree.flatten(grads, is_leaf=_is_sparse)
    flat_arrays = [a for l in leaves
                   for a in ((l.values, l.indices) if _is_sparse(l) else (l,))]
    if any(isinstance(l, jax.core.Tracer) for l in flat_arrays):
        axis = axis_name if isinstance(axis_name, str) else tuple(axis_name)
        raise RuntimeError(
            f"DistributedOptimizer/allreduce_gradients was traced inside "
            f"jit without the mesh axis {axis!r} in scope: the eager "
            f"fallback cannot run on tracers.  Run the update step via "
            f"horovod_tpu.jax.spmd.make_train_step (or your own "
            f"jax.shard_map over hvd.ranks_mesh()), or use the in-jit "
            f"collectives in horovod_tpu.ops.injit inside a plain jit.")
    if _sched.overlap_enabled(overlap):
        return _overlapped_allreduce(leaves, treedef, average=average,
                                     compression=compression,
                                     name_prefix=name_prefix, auto=auto)
    handles, ctxs = [], []
    for i, leaf in enumerate(leaves):
        if _is_sparse(leaf):
            # Sparse leaf: allgather values+indices (async pair so small
            # embedding grads still overlap with the dense handles).
            vh = _eager.allgather_async(_as_leaf(leaf.values),
                                        name=f"{name_prefix}.{i}.values")
            ih = _eager.allgather_async(_as_leaf(leaf.indices),
                                        name=f"{name_prefix}.{i}.indices")
            handles.append((vh, ih, leaf.dense_shape))
            ctxs.append(None)
            continue
        arr = _as_leaf(leaf)
        if jnp.result_type(arr) == jnp.float32:
            # float32 leaves keep their dtype and compress ON THE WIRE of
            # the cross-process ring instead (full-precision accumulate,
            # compressed transfer; also how HOROVOD_TPU_WIRE_DTYPE and
            # Compression.int8 take effect on the eager path).
            ctxs.append(None)
            handles.append(_eager.allreduce_async(
                arr, average=average, name=f"{name_prefix}.{i}",
                compression=compression))
            continue
        c, ctx = compression.compress(arr)
        ctxs.append(ctx)
        handles.append(_eager.allreduce_async(
            c, average=average, name=f"{name_prefix}.{i}"))
    outs = []
    for h, ctx in zip(handles, ctxs):
        if isinstance(h, tuple):
            vh, ih, dense_shape = h
            values = jnp.asarray(_eager.synchronize(vh))
            if average:
                values = values / basics.size()
            outs.append(_sparse.IndexedSlices(
                values, jnp.asarray(_eager.synchronize(ih)), dense_shape))
        else:
            outs.append(compression.decompress(
                jnp.asarray(_eager.synchronize(h)), ctx))
    if auto:
        for i, (leaf, out) in enumerate(zip(leaves, outs)):
            if not _is_sparse(leaf):
                _note_auto_residual(f"{name_prefix}.{i}", out)
    return jax.tree.unflatten(treedef, outs)


def _note_auto_residual(name: str, reduced, flat_ok: bool = False) -> None:
    """Feed the adaptive-precision autopilot one measured residual: the
    relative norm of the error the int8 grid (the ladder's most
    aggressive rung) would introduce on this reduced gradient.  bf16's
    error is strictly smaller, so one measurement bounds the whole
    ladder.  Reduced gradients are identical on every rank, so every
    process reports the same value and per-process mirrors stay in
    lockstep.  No-op unless ``HOROVOD_TPU_PRECISION=auto``."""
    from horovod_tpu import precision as _precision
    pilot = _precision.get_autopilot()
    if not pilot.enabled:
        return
    if jnp.result_type(reduced) != jnp.float32:
        return
    if flat_ok:
        # Fused overlap bucket: already a bulk 1-D payload — apply the
        # size floor only (int8_eligible's >=2-D test is a per-leaf rule).
        size = int(np.prod(jnp.shape(reduced))) if jnp.shape(reduced) else 1
        if size * 4 < _qc.int8_floor_bytes():
            return
    elif not _qc.int8_eligible(jnp.shape(reduced), jnp.result_type(reduced)):
        return
    g = jnp.asarray(reduced, dtype=jnp.float32)
    denom = float(jnp.linalg.norm(g.ravel()))
    if denom <= 0.0:
        pilot.note_residual(name, 0.0)
        return
    r = g - _qc.snap_to_grid(g)
    pilot.note_residual(name, float(jnp.linalg.norm(r.ravel())) / denom)


def _leaf_is_ready(arr) -> bool:
    """Device-readiness probe: True once the array's producing computation
    has finished (host numpy is always ready)."""
    probe = getattr(arr, "is_ready", None)
    if callable(probe):
        try:
            return bool(probe())
        except Exception:
            return True
    return True


def _overlapped_allreduce(leaves, treedef, *, average, compression,
                          name_prefix, auto: bool = False):
    """Backward-overlap eager reduction (HOROVOD_TPU_OVERLAP).

    float32 leaves are packed into scheduler buckets and each bucket's
    fused allreduce is enqueued as soon as its last gradient is ready on
    device — communication of early buckets hides under the backprop
    still producing later ones.  Sparse and non-float32 leaves keep the
    per-leaf submission of the non-overlapped path (same payloads, same
    math).  The bucket payload (concat of the bucket's leaves) does not
    depend on WHEN the bucket is issued, so results are bit-identical to
    ``overlap=False`` on the planes the test matrix covers (the fused
    negotiation path concatenates leaves the same way).

    Emits the ``overlap.hidden_seconds`` / ``overlap.exposed_seconds``
    pair per step: hidden = the part of the communication span that ran
    while gradients were still materializing, exposed = the tail the step
    actually waited on after backward finished.
    """
    from horovod_tpu import sparse as _sparse
    t_entry = time.perf_counter()
    arrs = [None if _is_sparse(l) else _as_leaf(l) for l in leaves]
    fp32 = [i for i, a in enumerate(arrs)
            if a is not None and jnp.result_type(a) == jnp.float32]
    outs: list = [None] * len(leaves)
    handles: dict = {}
    ctxs: dict = {}
    # Sparse and non-float32 leaves: submit up front, exactly like the
    # non-overlapped path.
    for i, leaf in enumerate(leaves):
        if _is_sparse(leaf):
            vh = _eager.allgather_async(_as_leaf(leaf.values),
                                        name=f"{name_prefix}.{i}.values")
            ih = _eager.allgather_async(_as_leaf(leaf.indices),
                                        name=f"{name_prefix}.{i}.indices")
            handles[i] = (vh, ih, leaf.dense_shape)
        elif i not in fp32:
            c, ctx = compression.compress(arrs[i])
            ctxs[i] = ctx
            handles[i] = _eager.allreduce_async(
                c, average=average, name=f"{name_prefix}.{i}")
    # Bucket the float32 leaves (declaration order; oversized leaves ride
    # alone) and drive readiness through the plane-agnostic scheduler.
    planner = _sched.make_bucket_planner(_sched.bucket_bytes_from_env())
    for j, i in enumerate(fp32):
        a = arrs[i]
        planner.register_leaf(f"{name_prefix}.{i}", a.size * a.dtype.itemsize,
                              "float32")
    n_buckets = planner.seal()
    bucket_leaves: list = [[] for _ in range(n_buckets)]
    for j, i in enumerate(fp32):
        bucket_leaves[planner.bucket_of(j)].append(i)
    bucket_handles: dict = {}
    issue_seq: list = []
    t_first_issue = None

    def _drain_issues():
        nonlocal t_first_issue
        while True:
            b = planner.next_issue()
            if b < 0:
                return
            if t_first_issue is None:
                t_first_issue = time.perf_counter()
            flat = np.concatenate(
                [np.asarray(arrs[i]).ravel() for i in bucket_leaves[b]]
            ) if len(bucket_leaves[b]) > 1 else np.asarray(
                arrs[bucket_leaves[b][0]]).ravel()
            bucket_handles[b] = _eager.allreduce_async(
                flat, average=average, name=f"{name_prefix}.bucket{b}",
                compression=compression)
            issue_seq.append(b)

    pending = set(range(len(fp32)))
    while pending:
        progressed = False
        for j in sorted(pending):
            if _leaf_is_ready(arrs[fp32[j]]):
                pending.discard(j)
                planner.note_ready(j)
                progressed = True
        _drain_issues()
        if pending and not progressed:
            time.sleep(50e-6)
    t_backward_done = time.perf_counter()
    # Synchronize buckets in issue order and scatter slices back.
    for b in issue_seq:
        red = np.asarray(_eager.synchronize(bucket_handles[b]))
        planner.note_complete(b)
        if auto:
            # The negotiated name under overlap is the BUCKET, so the
            # residual report (and the coordinator's dtype choice) is
            # per bucket too.
            _note_auto_residual(f"{name_prefix}.bucket{b}", red,
                                flat_ok=True)
        off = 0
        for i in bucket_leaves[b]:
            n = arrs[i].size
            piece = jnp.asarray(red[off:off + n]).reshape(arrs[i].shape)
            outs[i] = compression.decompress(piece, None)
            off += n
    t_comm_done = time.perf_counter()
    planner.close()
    if issue_seq and t_first_issue is not None:
        comm_span = max(0.0, t_comm_done - t_first_issue)
        exposed = max(0.0, t_comm_done - t_backward_done)
        hidden = max(0.0, comm_span - exposed)
        _metrics.inc("overlap.steps")
        _metrics.observe("overlap.hidden_seconds", hidden)
        _metrics.observe("overlap.exposed_seconds", exposed)
        if comm_span > 0:
            _metrics.observe("overlap.hidden_fraction", hidden / comm_span)
        # Observatory decomposition for the eager overlap step: the span
        # from entry to backward-done is compute (comm hides under it),
        # the post-backward tail is exposed comm, and whatever wall time
        # neither bucket accounts for is stall.
        from horovod_tpu import observe as _observe
        step_s = max(0.0, t_comm_done - t_entry)
        compute_s = max(0.0, t_backward_done - t_entry)
        stall_s = max(0.0, step_s - compute_s - exposed)
        _observe.note_step(step_s, compute_s, hidden, exposed, stall_s)
    # Drain the up-front (sparse / non-f32) handles.
    for i, h in handles.items():
        if isinstance(h, tuple):
            vh, ih, dense_shape = h
            values = jnp.asarray(_eager.synchronize(vh))
            if average:
                values = values / basics.size()
            outs[i] = _sparse.IndexedSlices(
                values, jnp.asarray(_eager.synchronize(ih)), dense_shape)
        else:
            outs[i] = compression.decompress(
                jnp.asarray(_eager.synchronize(h)), ctxs[i])
    return jax.tree.unflatten(treedef, outs)


def broadcast_parameters(params, root_rank: int = 0,
                         name_prefix: str = "broadcast.params"):
    """Broadcast a parameter pytree from ``root_rank`` to all ranks —
    startup state sync (reference ``horovod/torch/__init__.py:138-167``,
    ``BroadcastGlobalVariablesHook``)."""
    leaves, treedef = jax.tree.flatten(params)
    handles = [
        _eager.broadcast_async(_as_leaf(leaf), root_rank,
                               name=f"{name_prefix}.{i}")
        for i, leaf in enumerate(leaves)]
    outs = []
    for leaf, h in zip(leaves, handles):
        out = _eager.synchronize(h)
        out = jnp.asarray(out, dtype=jnp.result_type(leaf))
        outs.append(out)
    return jax.tree.unflatten(treedef, outs)


def broadcast_optimizer_state(opt_state, root_rank: int = 0,
                              name_prefix: str = "broadcast.opt"):
    """Broadcast optimizer state from ``root_rank``.

    The reference walks torch's state_dict, wrapping python scalars as
    tensors and restoring their types after the broadcast
    (``horovod/torch/__init__.py:170-263``).  An optax state is already a
    pytree; python-int leaves (e.g. step counters) get the same
    wrap-as-array / restore-type treatment.
    """
    leaves, treedef = jax.tree.flatten(opt_state)
    out_leaves = []
    for i, leaf in enumerate(leaves):
        was_int = isinstance(leaf, int) and not isinstance(leaf, bool)
        was_float = isinstance(leaf, float)
        arr = _as_leaf(leaf)
        res = _eager.broadcast(arr, root_rank, name=f"{name_prefix}.{i}")
        if was_int:
            out_leaves.append(int(np.asarray(res)))
        elif was_float:
            out_leaves.append(float(np.asarray(res)))
        else:
            out_leaves.append(jnp.asarray(res, dtype=jnp.result_type(arr)))
    return jax.tree.unflatten(treedef, out_leaves)


def allreduce_(tree, *, average: bool = True, name_prefix: str = "allreduce"):
    """Allreduce of an arbitrary pytree (metric averaging etc.)."""
    return allreduce_gradients(tree, average=average,
                               name_prefix=name_prefix, grads_hint=False)


__all__ = [
    "DistributedOptimizer", "ErrorFeedbackState", "allreduce_gradients",
    "broadcast_parameters", "broadcast_optimizer_state", "allreduce_",
    "Compression",
]
