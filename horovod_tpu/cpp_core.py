"""ctypes bridge to the native core (``cpp/htpu``, built as
``horovod_tpu/lib/libhtpu_core.so``).

Mirrors the reference's ctypes ``HorovodBasics`` pattern
(``horovod/common/__init__.py:51-84``): a narrow ``extern "C"`` API, bytes
in the htpu wire format (:mod:`horovod_tpu.wire`) as the interchange.

Exposes drop-in replacements for the control-plane classes in
:mod:`horovod_tpu.core`: :class:`CppMessageTable`, :func:`cpp_plan_fusion`,
:class:`CppTimeline`.  ``load()`` builds the library with ``make`` on first
use if it is missing (the toolchain is a build requirement, like the
reference's ``mpicxx``); set ``HOROVOD_TPU_NO_CPP=1`` to force the
pure-Python fallback.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import warnings
from typing import List

from horovod_tpu import wire
from horovod_tpu.core import Request, Response, env_flag

_LIB_PATH = os.path.join(os.path.dirname(__file__), "lib", "libhtpu_core.so")
_CPP_DIR = os.path.join(os.path.dirname(__file__), os.pardir, "cpp")

_lib = None
_lib_lock = threading.Lock()


def _configure(lib) -> None:
    lib.htpu_version.restype = ctypes.c_char_p
    lib.htpu_free.restype = None
    lib.htpu_free.argtypes = [ctypes.c_void_p]
    lib.htpu_table_create.restype = ctypes.c_void_p
    lib.htpu_table_create.argtypes = [ctypes.c_int]
    lib.htpu_table_destroy.restype = None
    lib.htpu_table_destroy.argtypes = [ctypes.c_void_p]
    lib.htpu_table_increment.restype = ctypes.c_int
    lib.htpu_table_increment.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    lib.htpu_table_construct_response.restype = ctypes.c_int
    lib.htpu_table_construct_response.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_void_p)]
    lib.htpu_table_num_pending.restype = ctypes.c_int
    lib.htpu_table_num_pending.argtypes = [ctypes.c_void_p]
    lib.htpu_table_clear.restype = None
    lib.htpu_table_clear.argtypes = [ctypes.c_void_p]
    lib.htpu_table_stalled.restype = ctypes.c_int
    lib.htpu_table_stalled.argtypes = [
        ctypes.c_void_p, ctypes.c_double, ctypes.POINTER(ctypes.c_void_p)]
    lib.htpu_table_configure_algo.restype = None
    lib.htpu_table_configure_algo.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_longlong]
    lib.htpu_plan_fusion.restype = ctypes.c_int
    lib.htpu_plan_fusion.argtypes = [
        ctypes.c_char_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_char_p), ctypes.c_int, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_void_p)]
    lib.htpu_timeline_create.restype = ctypes.c_void_p
    lib.htpu_timeline_create.argtypes = [ctypes.c_char_p]
    lib.htpu_timeline_destroy.restype = None
    lib.htpu_timeline_destroy.argtypes = [ctypes.c_void_p]
    # Newer symbols are guarded so a prebuilt library from an older round
    # still loads (the hasattr idiom used for htpu_wire_encode below).
    if hasattr(lib, "htpu_timeline_create_rank"):
        lib.htpu_timeline_create_rank.restype = ctypes.c_void_p
        lib.htpu_timeline_create_rank.argtypes = [
            ctypes.c_char_p, ctypes.c_int]
    if hasattr(lib, "htpu_timeline_instant"):
        lib.htpu_timeline_instant.restype = None
        lib.htpu_timeline_instant.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p]
    if hasattr(lib, "htpu_timeline_tick_span"):
        lib.htpu_timeline_tick_span.restype = None
        lib.htpu_timeline_tick_span.argtypes = [
            ctypes.c_void_p, ctypes.c_ulonglong, ctypes.c_longlong]
    for fn in ("negotiate_start", "start"):
        f = getattr(lib, f"htpu_timeline_{fn}")
        f.restype = None
        f.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    lib.htpu_timeline_negotiate_rank_ready.restype = None
    lib.htpu_timeline_negotiate_rank_ready.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    for fn in ("negotiate_end", "end", "activity_end"):
        f = getattr(lib, f"htpu_timeline_{fn}")
        f.restype = None
        f.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.htpu_timeline_activity_start.restype = None
    lib.htpu_timeline_activity_start.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p]
    lib.htpu_timeline_counter.restype = None
    lib.htpu_timeline_counter.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_longlong]
    lib.htpu_timeline_cache_hit_tick.restype = None
    lib.htpu_timeline_cache_hit_tick.argtypes = [
        ctypes.c_void_p, ctypes.c_longlong]
    lib.htpu_timeline_flush.restype = None
    lib.htpu_timeline_flush.argtypes = [ctypes.c_void_p]
    lib.htpu_timeline_close.restype = None
    lib.htpu_timeline_close.argtypes = [ctypes.c_void_p]
    lib.htpu_control_create.restype = ctypes.c_void_p
    lib.htpu_control_create.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int]
    lib.htpu_control_destroy.restype = None
    lib.htpu_control_destroy.argtypes = [ctypes.c_void_p]
    lib.htpu_control_tick.restype = ctypes.c_int
    lib.htpu_control_tick.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_longlong,
        ctypes.POINTER(ctypes.c_void_p)]
    lib.htpu_control_allreduce.restype = ctypes.c_int
    lib.htpu_control_allreduce.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
        ctypes.c_longlong, ctypes.POINTER(ctypes.c_void_p)]
    lib.htpu_control_allreduce_wire.restype = ctypes.c_int
    lib.htpu_control_allreduce_wire.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_void_p,
        ctypes.c_longlong, ctypes.POINTER(ctypes.c_void_p)]
    lib.htpu_control_allreduce_algo.restype = ctypes.c_int
    lib.htpu_control_allreduce_algo.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_void_p, ctypes.c_longlong, ctypes.POINTER(ctypes.c_void_p)]
    lib.htpu_wire_roundtrip.restype = ctypes.c_longlong
    lib.htpu_wire_roundtrip.argtypes = [
        ctypes.c_char_p, ctypes.c_void_p, ctypes.c_longlong,
        ctypes.c_void_p]
    for fn in ("htpu_wire_encode", "htpu_wire_decode"):
        f = getattr(lib, fn, None)
        if f is not None:
            f.restype = ctypes.c_longlong
            f.argtypes = [ctypes.c_char_p, ctypes.c_void_p,
                          ctypes.c_longlong, ctypes.c_void_p]
    if hasattr(lib, "htpu_wire_bytes"):
        lib.htpu_wire_bytes.restype = ctypes.c_longlong
        lib.htpu_wire_bytes.argtypes = [ctypes.c_char_p, ctypes.c_longlong]
    lib.htpu_sum_into.restype = ctypes.c_int
    lib.htpu_sum_into.argtypes = [
        ctypes.c_char_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_longlong]
    lib.htpu_control_allgather.restype = ctypes.c_int
    lib.htpu_control_allgather.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_longlong,
        ctypes.POINTER(ctypes.c_void_p)]
    lib.htpu_control_broadcast.restype = ctypes.c_int
    lib.htpu_control_broadcast.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_longlong,
        ctypes.POINTER(ctypes.c_void_p)]
    lib.htpu_control_stalled.restype = ctypes.c_int
    lib.htpu_control_stalled.argtypes = [
        ctypes.c_void_p, ctypes.c_double, ctypes.POINTER(ctypes.c_void_p)]
    lib.htpu_control_last_error.restype = ctypes.c_int
    lib.htpu_control_last_error.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_void_p)]
    lib.htpu_control_data_bytes.restype = None
    lib.htpu_control_data_bytes.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_longlong),
        ctypes.POINTER(ctypes.c_longlong)]
    if hasattr(lib, "htpu_control_membership"):
        lib.htpu_control_membership.restype = None
        lib.htpu_control_membership.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int)]
        lib.htpu_control_elastic.restype = ctypes.c_int
        lib.htpu_control_elastic.argtypes = [ctypes.c_void_p]
    lib.htpu_control_ring_transport.restype = ctypes.c_char_p
    lib.htpu_control_ring_transport.argtypes = [ctypes.c_void_p]
    lib.htpu_control_data_transport.restype = ctypes.c_char_p
    lib.htpu_control_data_transport.argtypes = [ctypes.c_void_p]
    lib.htpu_control_set_timeline.restype = None
    lib.htpu_control_set_timeline.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p]
    lib.htpu_metrics_snapshot.restype = ctypes.c_int
    lib.htpu_metrics_snapshot.argtypes = [ctypes.POINTER(ctypes.c_void_p)]
    lib.htpu_metrics_reset.restype = None
    lib.htpu_metrics_reset.argtypes = []
    if hasattr(lib, "htpu_flight_record"):
        lib.htpu_flight_record.restype = None
        lib.htpu_flight_record.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_longlong,
            ctypes.c_int, ctypes.c_int]
        lib.htpu_flight_set_capacity.restype = None
        lib.htpu_flight_set_capacity.argtypes = [ctypes.c_longlong]
        lib.htpu_flight_set_rank.restype = None
        lib.htpu_flight_set_rank.argtypes = [ctypes.c_int]
        lib.htpu_flight_dump.restype = ctypes.c_int
        lib.htpu_flight_dump.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_void_p)]
        lib.htpu_flight_snapshot.restype = ctypes.c_int
        lib.htpu_flight_snapshot.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_void_p)]
    # Fleet observatory (guarded: a prebuilt .so from before the
    # observatory still loads for the rest of the surface).
    if hasattr(lib, "htpu_observe_enabled"):
        lib.htpu_observe_enabled.restype = ctypes.c_int
        lib.htpu_observe_enabled.argtypes = []
        lib.htpu_observe_set_enabled.restype = None
        lib.htpu_observe_set_enabled.argtypes = [ctypes.c_int]
        lib.htpu_observe_note_step.restype = None
        lib.htpu_observe_note_step.argtypes = [
            ctypes.c_double, ctypes.c_double, ctypes.c_double,
            ctypes.c_double, ctypes.c_double]
        lib.htpu_observe_record_xfer.restype = None
        lib.htpu_observe_record_xfer.argtypes = [
            ctypes.c_int, ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_double]
        lib.htpu_observe_snapshot.restype = ctypes.c_int
        lib.htpu_observe_snapshot.argtypes = [
            ctypes.POINTER(ctypes.c_void_p)]
        lib.htpu_observe_reset.restype = None
        lib.htpu_observe_reset.argtypes = []
        lib.htpu_observe_trailer_encode.restype = ctypes.c_int
        lib.htpu_observe_trailer_encode.argtypes = [
            ctypes.POINTER(ctypes.c_void_p)]
        lib.htpu_observe_trailer_probe.restype = ctypes.c_int
        lib.htpu_observe_trailer_probe.argtypes = [
            ctypes.c_char_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_void_p)]
    # Aggregation tier (guarded: a prebuilt .so predating the
    # hierarchical control topology still loads for the rest of the
    # surface).
    if hasattr(lib, "htpu_agg_merge"):
        lib.htpu_agg_merge.restype = ctypes.c_int
        lib.htpu_agg_merge.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_void_p)]
        lib.htpu_agg_roundtrip.restype = ctypes.c_int
        lib.htpu_agg_roundtrip.argtypes = [
            ctypes.c_char_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_void_p)]
    # Scheduler API (guarded: a prebuilt .so predating the plane-agnostic
    # scheduler still loads for the rest of the surface).
    if hasattr(lib, "htpu_sched_create"):
        lib.htpu_plan_tick.restype = ctypes.c_int
        lib.htpu_plan_tick.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_char_p),
            ctypes.c_int, ctypes.c_int64, ctypes.POINTER(ctypes.c_void_p)]
        lib.htpu_resolve_algo.restype = ctypes.c_int
        lib.htpu_resolve_algo.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int, ctypes.c_int,
            ctypes.c_int64, ctypes.POINTER(ctypes.c_void_p)]
        lib.htpu_sched_create.restype = ctypes.c_void_p
        lib.htpu_sched_create.argtypes = [ctypes.c_int64]
        lib.htpu_sched_destroy.restype = None
        lib.htpu_sched_destroy.argtypes = [ctypes.c_void_p]
        lib.htpu_sched_register.restype = ctypes.c_int
        lib.htpu_sched_register.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_char_p]
        lib.htpu_sched_seal.restype = ctypes.c_int
        lib.htpu_sched_seal.argtypes = [ctypes.c_void_p]
        lib.htpu_sched_bucket_of.restype = ctypes.c_int
        lib.htpu_sched_bucket_of.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.htpu_sched_bucket_bytes.restype = ctypes.c_int64
        lib.htpu_sched_bucket_bytes.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.htpu_sched_note_ready.restype = ctypes.c_int
        lib.htpu_sched_note_ready.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.htpu_sched_next_issue.restype = ctypes.c_int
        lib.htpu_sched_next_issue.argtypes = [ctypes.c_void_p]
        lib.htpu_sched_note_complete.restype = None
        lib.htpu_sched_note_complete.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.htpu_sched_all_complete.restype = ctypes.c_int
        lib.htpu_sched_all_complete.argtypes = [ctypes.c_void_p]
        lib.htpu_sched_reset.restype = None
        lib.htpu_sched_reset.argtypes = [ctypes.c_void_p]
    # Fleet-policy API (guarded like the scheduler: a prebuilt .so from
    # before the policy engine still loads for the rest of the surface).
    if hasattr(lib, "htpu_policy_create"):
        lib.htpu_policy_create.restype = ctypes.c_void_p
        lib.htpu_policy_create.argtypes = []
        lib.htpu_policy_destroy.restype = None
        lib.htpu_policy_destroy.argtypes = [ctypes.c_void_p]
        lib.htpu_policy_active.restype = ctypes.c_int
        lib.htpu_policy_active.argtypes = [ctypes.c_void_p]
        lib.htpu_policy_observe.restype = None
        lib.htpu_policy_observe.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.POINTER(ctypes.c_double),
            ctypes.c_int]
        lib.htpu_policy_next_eviction.restype = ctypes.c_int
        lib.htpu_policy_next_eviction.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
        lib.htpu_policy_rerank.restype = None
        lib.htpu_policy_rerank.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int), ctypes.c_int]
        lib.htpu_policy_autoscale_target.restype = ctypes.c_int
        lib.htpu_policy_autoscale_target.argtypes = [
            ctypes.c_void_p, ctypes.c_int64]
        lib.htpu_policy_ewma.restype = ctypes.c_double
        lib.htpu_policy_ewma.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.htpu_policy_consecutive_slow.restype = ctypes.c_int
        lib.htpu_policy_consecutive_slow.argtypes = [
            ctypes.c_void_p, ctypes.c_int]
    # Per-set straggler state (PR 15); hasattr-guarded so a prebuilt .so
    # that predates process sets still loads.
    if hasattr(lib, "htpu_policy_observe_set"):
        lib.htpu_policy_observe_set.restype = None
        lib.htpu_policy_observe_set.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_double),
            ctypes.c_int]
        lib.htpu_policy_ewma_set.restype = ctypes.c_double
        lib.htpu_policy_ewma_set.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
        lib.htpu_policy_consecutive_slow_set.restype = ctypes.c_int
        lib.htpu_policy_consecutive_slow_set.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
        lib.htpu_policy_next_eviction_set.restype = ctypes.c_int
        lib.htpu_policy_next_eviction_set.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int]
    # Precision controller (PR 19), same guard: a prebuilt .so from
    # before the autopilot still loads for the rest of the surface.
    if hasattr(lib, "htpu_policy_precision_auto"):
        lib.htpu_policy_precision_auto.restype = ctypes.c_int
        lib.htpu_policy_precision_auto.argtypes = [ctypes.c_void_p]
        lib.htpu_policy_precision_observe.restype = None
        lib.htpu_policy_precision_observe.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_double]
        lib.htpu_policy_precision_bandwidth.restype = None
        lib.htpu_policy_precision_bandwidth.argtypes = [
            ctypes.c_void_p, ctypes.c_double]
        lib.htpu_policy_precision_level.restype = ctypes.c_int
        lib.htpu_policy_precision_level.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p]
        lib.htpu_policy_precision_ewma.restype = ctypes.c_double
        lib.htpu_policy_precision_ewma.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p]
        lib.htpu_policy_precision_counts.restype = None
        lib.htpu_policy_precision_counts.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_longlong)]
        lib.htpu_policy_precision_dirty.restype = ctypes.c_int
        lib.htpu_policy_precision_dirty.argtypes = [ctypes.c_void_p]
    if hasattr(lib, "htpu_wire_request_list_roundtrip"):
        lib.htpu_wire_request_list_roundtrip.restype = ctypes.c_longlong
        lib.htpu_wire_request_list_roundtrip.argtypes = [
            ctypes.c_void_p, ctypes.c_longlong, ctypes.c_void_p,
            ctypes.c_longlong]
    # Multi-tenant process-set registry (PR 15), same guard.
    if hasattr(lib, "htpu_process_sets_create"):
        lib.htpu_process_sets_create.restype = ctypes.c_void_p
        lib.htpu_process_sets_create.argtypes = [ctypes.c_longlong]
        lib.htpu_process_sets_destroy.restype = None
        lib.htpu_process_sets_destroy.argtypes = [ctypes.c_void_p]
        lib.htpu_process_sets_parse_spec.restype = ctypes.c_int
        lib.htpu_process_sets_parse_spec.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p]
        lib.htpu_process_sets_add.restype = ctypes.c_int
        lib.htpu_process_sets_add.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
            ctypes.c_int]
        lib.htpu_process_sets_remove.restype = ctypes.c_int
        lib.htpu_process_sets_remove.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.htpu_process_sets_id_of.restype = ctypes.c_int
        lib.htpu_process_sets_id_of.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p]
        lib.htpu_process_sets_count.restype = ctypes.c_int
        lib.htpu_process_sets_count.argtypes = [ctypes.c_void_p]
        lib.htpu_process_sets_size.restype = ctypes.c_int
        lib.htpu_process_sets_size.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.htpu_process_sets_local_rank.restype = ctypes.c_int
        lib.htpu_process_sets_local_rank.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
        lib.htpu_process_sets_generation.restype = ctypes.c_int
        lib.htpu_process_sets_generation.argtypes = [
            ctypes.c_void_p, ctypes.c_int]
        lib.htpu_process_sets_reconfigure.restype = ctypes.c_int
        lib.htpu_process_sets_reconfigure.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
        lib.htpu_process_sets_increment.restype = ctypes.c_int
        lib.htpu_process_sets_increment.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
        lib.htpu_process_sets_construct.restype = ctypes.c_int
        lib.htpu_process_sets_construct.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_void_p)]
    # Integrity plane (PR 17: CRC32C + checked transfers), same guard —
    # a prebuilt .so from before the integrity layer still loads.
    if hasattr(lib, "htpu_crc32c"):
        lib.htpu_crc32c.restype = ctypes.c_uint
        lib.htpu_crc32c.argtypes = [ctypes.c_void_p, ctypes.c_longlong]
        lib.htpu_crc32c_sw.restype = ctypes.c_uint
        lib.htpu_crc32c_sw.argtypes = [ctypes.c_void_p, ctypes.c_longlong]
        lib.htpu_crc32c_hw.restype = ctypes.c_int
        lib.htpu_crc32c_hw.argtypes = []
        lib.htpu_control_set_xfer_context.restype = None
        lib.htpu_control_set_xfer_context.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p]


def load():
    """Load (building if necessary) the native core; None if unavailable."""
    global _lib
    if env_flag("HOROVOD_TPU_NO_CPP"):
        return None
    with _lib_lock:
        if _lib is not None:
            return _lib
        if os.path.isdir(_CPP_DIR):
            # Run make even when the .so exists: it no-ops when up to date
            # and rebuilds a stale library whose symbols predate this module.
            try:
                subprocess.run(["make", "-C", _CPP_DIR], check=True,
                               capture_output=True, timeout=120)
            except subprocess.CalledProcessError as e:
                # Fall through: a prebuilt .so may still be usable — but say
                # so, or the pure-Python fallback engages silently.
                warnings.warn(
                    "horovod_tpu: native core build failed; falling back to "
                    "the pure-Python control path if no prebuilt library "
                    "exists.\n--- make stderr ---\n"
                    + e.stderr.decode(errors="replace")[-2000:],
                    RuntimeWarning)
            except (subprocess.SubprocessError, OSError) as e:
                warnings.warn(
                    f"horovod_tpu: native core build did not run ({e}); "
                    "falling back to the pure-Python control path if no "
                    "prebuilt library exists.", RuntimeWarning)
        if not os.path.exists(_LIB_PATH):
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
            _configure(lib)
        except (OSError, AttributeError) as e:
            # AttributeError = stale library missing newer symbols.
            warnings.warn(
                f"horovod_tpu: native core library unusable ({e}); using "
                "the pure-Python control path.", RuntimeWarning)
            return None
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


def _take_buffer(lib, out_ptr: ctypes.c_void_p, length: int) -> bytes:
    if length < 0:
        raise RuntimeError("native core returned an error")
    try:
        if length == 0:
            return b""
        return ctypes.string_at(out_ptr, length)
    finally:
        lib.htpu_free(out_ptr)


# ------------------------------------------------------- flight recorder

def _flight_lib():
    """The loaded library iff it exports the flight-recorder API, else
    None — every helper below degrades to a no-op on a pure-Python run or
    a stale prebuilt .so."""
    lib = load()
    if lib is None or not hasattr(lib, "htpu_flight_record"):
        return None
    return lib


def flight_record(kind: str, detail: str = "", nbytes: int = 0,
                  a: int = 0, b: int = 0) -> None:
    """Append one event to the native flight-recorder ring (no-op without
    the native core).  Python-side callers use this to mark host-level
    context — op-timeout pending tensors, shutdown phases — so the abort
    dump interleaves them with the C++ tick/transfer events."""
    lib = _flight_lib()
    if lib is not None:
        lib.htpu_flight_record(kind.encode("utf-8"), detail.encode("utf-8"),
                               int(nbytes), int(a), int(b))


def flight_set_capacity(events: int) -> None:
    lib = _flight_lib()
    if lib is not None:
        lib.htpu_flight_set_capacity(int(events))


def flight_set_rank(rank: int) -> None:
    lib = _flight_lib()
    if lib is not None:
        lib.htpu_flight_set_rank(int(rank))


def flight_dump(why: str = "manual") -> str:
    """Dump the ring to its per-rank JSON file; returns the path, or ""
    when the dump failed or the native core is absent."""
    lib = _flight_lib()
    if lib is None:
        return ""
    out = ctypes.c_void_p()
    n = lib.htpu_flight_dump(why.encode("utf-8"), ctypes.byref(out))
    if n < 0:
        return ""
    return _take_buffer(lib, out, n).decode("utf-8", errors="replace")


def flight_snapshot(why: str = "snapshot") -> str:
    """The ring serialized as JSON (without touching disk); "" when the
    native core is absent."""
    lib = _flight_lib()
    if lib is None:
        return ""
    out = ctypes.c_void_p()
    n = lib.htpu_flight_snapshot(why.encode("utf-8"), ctypes.byref(out))
    if n < 0:
        return ""
    return _take_buffer(lib, out, n).decode("utf-8", errors="replace")


class CppMessageTable:
    """Native MessageTable with the Python-class interface of
    :class:`horovod_tpu.core.MessageTable`."""

    def __init__(self, size: int, timeline=None):
        self._lib = load()
        if self._lib is None:
            raise RuntimeError("native core not available")
        self._ptr = self._lib.htpu_table_create(size)
        self._size = size
        self._timeline = timeline
        self._pending_names = set()   # for timeline negotiate_start hooks

    def __del__(self):
        lib, ptr = getattr(self, "_lib", None), getattr(self, "_ptr", None)
        if lib is not None and ptr:
            lib.htpu_table_destroy(ptr)
            self._ptr = None

    def __len__(self):
        return self._lib.htpu_table_num_pending(self._ptr)

    def clear(self):
        self._lib.htpu_table_clear(self._ptr)
        self._pending_names.clear()

    def increment(self, msg: Request) -> bool:
        # Single-message boundary frames always carry the algo field (the
        # C side parses with with_algo=true — no flag byte on this path).
        data = wire.serialize_request(msg, with_algo=True)
        rc = self._lib.htpu_table_increment(self._ptr, data, len(data))
        if rc < 0:
            raise RuntimeError("native core failed to parse request")
        if self._timeline:
            # The native table doesn't call back into Python; replicate the
            # negotiation hooks here, tracking first-appearance locally.
            if msg.tensor_name not in self._pending_names:
                self._pending_names.add(msg.tensor_name)
                self._timeline.negotiate_start(msg.tensor_name,
                                               msg.request_type)
            self._timeline.negotiate_rank_ready(msg.tensor_name,
                                                msg.request_rank)
            if rc == 1:
                self._timeline.negotiate_end(msg.tensor_name)
        return rc == 1

    def construct_response(self, name: str) -> Response:
        self._pending_names.discard(name)
        out = ctypes.c_void_p()
        n = self._lib.htpu_table_construct_response(
            self._ptr, name.encode("utf-8"), ctypes.byref(out))
        return wire.parse_single_response(_take_buffer(self._lib, out, n))

    def pending_names_older_than(self, age_s: float):
        out = ctypes.c_void_p()
        n = self._lib.htpu_table_stalled(self._ptr, age_s, ctypes.byref(out))
        return _parse_stall_records(_take_buffer(self._lib, out, n))

    def configure_algo_selection(self, num_hosts: int, num_procs: int,
                                 crossover_bytes: int) -> None:
        """Topology + crossover inputs for allreduce algorithm resolution
        ("auto" -> ring / hier / small per payload size)."""
        self._lib.htpu_table_configure_algo(
            self._ptr, num_hosts, num_procs, crossover_bytes)


def cpp_plan_fusion(responses: List[Response], entry_bytes, entry_dtype,
                    threshold: int) -> List[Response]:
    """Native fusion planner with the signature of
    :func:`horovod_tpu.core.plan_fusion`."""
    lib = load()
    if lib is None:
        raise RuntimeError("native core not available")
    blob = wire.serialize_response_list(responses)
    names = sorted({n for r in responses for n in r.tensor_names})
    n = len(names)
    name_arr = (ctypes.c_char_p * n)(*[s.encode("utf-8") for s in names])
    bytes_arr = (ctypes.c_int64 * n)(*[entry_bytes(s) for s in names])
    dtype_arr = (ctypes.c_char_p * n)(
        *[entry_dtype(s).encode("utf-8") for s in names])
    out = ctypes.c_void_p()
    rc = lib.htpu_plan_fusion(blob, len(blob), name_arr, bytes_arr, dtype_arr,
                              n, threshold, ctypes.byref(out))
    fused, _, _ = wire.parse_response_list(_take_buffer(lib, out, rc))
    return fused


def _sched_lib():
    """The loaded library iff it exports the plane-agnostic scheduler API,
    else None (pure-Python run or stale prebuilt .so)."""
    lib = load()
    if lib is None or not hasattr(lib, "htpu_sched_create"):
        return None
    return lib


def cpp_plan_tick(responses: List[Response], entry_bytes, entry_dtype,
                  threshold: int) -> List[Response]:
    """Native per-tick policy (fusion + first-ready issue order) with the
    signature of :func:`horovod_tpu.scheduler.plan_tick`."""
    lib = _sched_lib()
    if lib is None:
        return cpp_plan_fusion(responses, entry_bytes, entry_dtype, threshold)
    blob = wire.serialize_response_list(responses)
    names = sorted({n for r in responses for n in r.tensor_names})
    n = len(names)
    name_arr = (ctypes.c_char_p * n)(*[s.encode("utf-8") for s in names])
    bytes_arr = (ctypes.c_int64 * n)(*[entry_bytes(s) for s in names])
    dtype_arr = (ctypes.c_char_p * n)(
        *[entry_dtype(s).encode("utf-8") for s in names])
    out = ctypes.c_void_p()
    rc = lib.htpu_plan_tick(blob, len(blob), name_arr, bytes_arr, dtype_arr,
                            n, threshold, ctypes.byref(out))
    fused, _, _ = wire.parse_response_list(_take_buffer(lib, out, rc))
    return fused


def cpp_resolve_algo(pref: str, nbytes: int, num_hosts: int, num_procs: int,
                     crossover_bytes: int) -> str:
    """Native allreduce-algorithm selection ("" = flat ring)."""
    lib = _sched_lib()
    if lib is None:
        raise RuntimeError("native scheduler not available")
    out = ctypes.c_void_p()
    rc = lib.htpu_resolve_algo(pref.encode("utf-8"), nbytes, num_hosts,
                               num_procs, crossover_bytes, ctypes.byref(out))
    return _take_buffer(lib, out, rc).decode("utf-8")


class NativeBucketPlanner:
    """ctypes wrapper over the C++ backward-overlap bucket planner.  Same
    surface as the pure-Python fallback in horovod_tpu/scheduler.py."""

    def __init__(self, bucket_bytes: int):
        lib = _sched_lib()
        if lib is None:
            raise RuntimeError("native scheduler not available")
        self._lib = lib
        self._ptr = lib.htpu_sched_create(int(bucket_bytes))

    def close(self) -> None:
        if self._ptr:
            self._lib.htpu_sched_destroy(self._ptr)
            self._ptr = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def register_leaf(self, name: str, nbytes: int, dtype: str) -> int:
        return self._lib.htpu_sched_register(
            self._ptr, name.encode("utf-8"), int(nbytes),
            dtype.encode("utf-8"))

    def seal(self) -> int:
        return self._lib.htpu_sched_seal(self._ptr)

    def bucket_of(self, leaf: int) -> int:
        return self._lib.htpu_sched_bucket_of(self._ptr, int(leaf))

    def bucket_bytes(self, bucket: int) -> int:
        return self._lib.htpu_sched_bucket_bytes(self._ptr, int(bucket))

    def note_ready(self, leaf: int) -> int:
        return self._lib.htpu_sched_note_ready(self._ptr, int(leaf))

    def next_issue(self) -> int:
        return self._lib.htpu_sched_next_issue(self._ptr)

    def note_complete(self, bucket: int) -> None:
        self._lib.htpu_sched_note_complete(self._ptr, int(bucket))

    def all_complete(self) -> bool:
        return bool(self._lib.htpu_sched_all_complete(self._ptr))

    def reset(self) -> None:
        self._lib.htpu_sched_reset(self._ptr)


def _policy_lib():
    """The loaded library iff it exports the fleet-policy API, else None
    (pure-Python run or stale prebuilt .so)."""
    lib = load()
    if lib is None or not hasattr(lib, "htpu_policy_create"):
        return None
    return lib


class NativeFleetPolicy:
    """ctypes wrapper over the C++ fleet-policy decision engine.  Covers
    the decision surface (observe/evict/rerank/autoscale plus the ewma
    and consecutive-slow probes) of the pure-Python mirror in
    horovod_tpu/policy.py; used for parity tests and offline replay —
    the in-job native policy lives inside the ControlPlane itself."""

    def __init__(self):
        lib = _policy_lib()
        if lib is None:
            raise RuntimeError("native fleet policy not available")
        self._lib = lib
        self._ptr = lib.htpu_policy_create()

    def close(self) -> None:
        if self._ptr:
            self._lib.htpu_policy_destroy(self._ptr)
            self._ptr = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def active(self) -> bool:
        return bool(self._lib.htpu_policy_active(self._ptr))

    def observe_tick(self, tick: int, wait_s) -> None:
        n = len(wait_s)
        arr = (ctypes.c_double * n)(*[float(w) for w in wait_s])
        self._lib.htpu_policy_observe(self._ptr, int(tick), arr, n)

    def next_eviction(self, process_count: int, seat_available: bool) -> int:
        return self._lib.htpu_policy_next_eviction(
            self._ptr, int(process_count), 1 if seat_available else 0)

    def rerank_order(self, old_pidx):
        n = len(old_pidx)
        arr = (ctypes.c_int * n)(*[int(p) for p in old_pidx])
        self._lib.htpu_policy_rerank(self._ptr, arr, n)
        return list(arr)

    def autoscale_target(self, tick: int) -> int:
        return self._lib.htpu_policy_autoscale_target(self._ptr, int(tick))

    def ewma(self, proc: int) -> float:
        return float(self._lib.htpu_policy_ewma(self._ptr, int(proc)))

    def consecutive_slow(self, proc: int) -> int:
        return self._lib.htpu_policy_consecutive_slow(self._ptr, int(proc))

    # -- per-set straggler state (PR 15).  A stale .so without the set
    # endpoints raises, matching the parity tests' skip condition.

    def observe_tick_set(self, process_set: int, wait_s) -> None:
        if not hasattr(self._lib, "htpu_policy_observe_set"):
            raise RuntimeError("native per-set policy not available")
        n = len(wait_s)
        arr = (ctypes.c_double * n)(*[float(w) for w in wait_s])
        self._lib.htpu_policy_observe_set(self._ptr, int(process_set), arr, n)

    def ewma_set(self, process_set: int, proc: int) -> float:
        if not hasattr(self._lib, "htpu_policy_ewma_set"):
            raise RuntimeError("native per-set policy not available")
        return float(self._lib.htpu_policy_ewma_set(
            self._ptr, int(process_set), int(proc)))

    def consecutive_slow_set(self, process_set: int, proc: int) -> int:
        if not hasattr(self._lib, "htpu_policy_consecutive_slow_set"):
            raise RuntimeError("native per-set policy not available")
        return self._lib.htpu_policy_consecutive_slow_set(
            self._ptr, int(process_set), int(proc))

    def next_eviction_set(self, process_set: int, process_count: int,
                          seat_available: bool) -> int:
        if not hasattr(self._lib, "htpu_policy_next_eviction_set"):
            raise RuntimeError("native per-set policy not available")
        return self._lib.htpu_policy_next_eviction_set(
            self._ptr, int(process_set), int(process_count),
            1 if seat_available else 0)

    # -- precision controller (PR 19).  A stale .so without the
    # precision endpoints raises, matching the parity tests' skip
    # condition.

    def _precision_lib(self):
        if not hasattr(self._lib, "htpu_policy_precision_auto"):
            raise RuntimeError("native precision controller not available")
        return self._lib

    def precision_auto(self) -> bool:
        return bool(self._precision_lib().htpu_policy_precision_auto(
            self._ptr))

    def observe_precision(self, name: str, residual_norm: float) -> None:
        self._precision_lib().htpu_policy_precision_observe(
            self._ptr, name.encode(), float(residual_norm))

    def note_precision_bandwidth(self, min_leg_bps: float) -> None:
        self._precision_lib().htpu_policy_precision_bandwidth(
            self._ptr, float(min_leg_bps))

    def precision_level(self, name: str) -> int:
        return self._precision_lib().htpu_policy_precision_level(
            self._ptr, name.encode())

    def precision_wire(self, name: str) -> str:
        from .policy import PRECISION_WIRE
        return PRECISION_WIRE[self.precision_level(name)]

    def precision_ewma(self, name: str) -> float:
        return float(self._precision_lib().htpu_policy_precision_ewma(
            self._ptr, name.encode()))

    @property
    def precision_promotions(self) -> int:
        counts = (ctypes.c_longlong * 2)()
        self._precision_lib().htpu_policy_precision_counts(self._ptr, counts)
        return int(counts[0])

    @property
    def precision_demotions(self) -> int:
        counts = (ctypes.c_longlong * 2)()
        self._precision_lib().htpu_policy_precision_counts(self._ptr, counts)
        return int(counts[1])

    def take_precision_dirty(self) -> bool:
        return bool(self._precision_lib().htpu_policy_precision_dirty(
            self._ptr))


def wire_request_list_roundtrip(frame: bytes):
    """Parse + re-serialize a RequestList frame through the native codec
    (the py<->cpp framing parity hook; payload codecs have their own
    htpu_wire_encode/decode endpoints).  Returns the re-serialized bytes,
    or None when the loaded .so predates the endpoint.  Raises
    ValueError when the native parser rejects the frame."""
    lib = load()
    if lib is None or not hasattr(lib, "htpu_wire_request_list_roundtrip"):
        return None
    cap = len(frame) + 64
    out = ctypes.create_string_buffer(cap)
    n = lib.htpu_wire_request_list_roundtrip(frame, len(frame), out, cap)
    if n < 0:
        raise ValueError("native RequestList parse rejected the frame")
    return out.raw[:n]


def _process_sets_lib():
    """The loaded library iff it exports the process-set API, else None
    (pure-Python run or stale prebuilt .so)."""
    lib = load()
    if lib is None or not hasattr(lib, "htpu_process_sets_create"):
        return None
    return lib


class CppProcessSetTable:
    """ctypes wrapper over the native multi-tenant process-set registry
    (cpp/htpu/process_set.h), with the interface of the Python mirror in
    horovod_tpu/process_set.py.  Set ids start at 1; 0 is the implicit
    default/world set."""

    def __init__(self, cache_capacity: int = 0):
        lib = _process_sets_lib()
        if lib is None:
            raise RuntimeError("native process sets not available")
        self._lib = lib
        self._ptr = lib.htpu_process_sets_create(int(cache_capacity))

    def close(self) -> None:
        if self._ptr:
            self._lib.htpu_process_sets_destroy(self._ptr)
            self._ptr = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def parse_spec(self, spec: str) -> bool:
        return bool(self._lib.htpu_process_sets_parse_spec(
            self._ptr, spec.encode("utf-8")))

    def add(self, name: str, ranks) -> int:
        n = len(ranks)
        arr = (ctypes.c_int * n)(*[int(r) for r in ranks])
        return self._lib.htpu_process_sets_add(
            self._ptr, name.encode("utf-8"), arr, n)

    def remove(self, set_id: int) -> bool:
        return bool(self._lib.htpu_process_sets_remove(self._ptr,
                                                       int(set_id)))

    def id_of(self, name: str) -> int:
        return self._lib.htpu_process_sets_id_of(self._ptr,
                                                 name.encode("utf-8"))

    def count(self) -> int:
        return self._lib.htpu_process_sets_count(self._ptr)

    def size_of(self, set_id: int) -> int:
        return self._lib.htpu_process_sets_size(self._ptr, int(set_id))

    def local_rank(self, set_id: int, global_rank: int) -> int:
        return self._lib.htpu_process_sets_local_rank(
            self._ptr, int(set_id), int(global_rank))

    def generation(self, set_id: int) -> int:
        return self._lib.htpu_process_sets_generation(self._ptr, int(set_id))

    def reconfigure(self, set_id: int, lost_global_rank: int) -> int:
        return self._lib.htpu_process_sets_reconfigure(
            self._ptr, int(set_id), int(lost_global_rank))

    def increment(self, set_id: int, msg: Request) -> int:
        # Same single-message boundary format as CppMessageTable.increment
        # (always with_algo; the set id is the explicit arg, never re-read
        # from the frame).
        data = wire.serialize_request(msg, with_algo=True)
        return self._lib.htpu_process_sets_increment(
            self._ptr, int(set_id), data, len(data))

    def construct_response(self, set_id: int, name: str) -> Response:
        out = ctypes.c_void_p()
        n = self._lib.htpu_process_sets_construct(
            self._ptr, int(set_id), name.encode("utf-8"), ctypes.byref(out))
        if n < 0:
            raise KeyError(f"unknown process set {set_id}")
        resp = wire.parse_single_response(_take_buffer(self._lib, out, n))
        resp.process_set = int(set_id)
        return resp


def wire_roundtrip(wire_dtype: str, values):
    """Encode → decode a float32 array through the ring wire codec
    (chunked exactly like the data plane); returns ``(decoded, wire_bytes)``.
    Unit-test hook for the quantizers — no sockets involved."""
    import numpy as np
    lib = load()
    if lib is None:
        raise RuntimeError("native core not available")
    arr = np.ascontiguousarray(values, dtype=np.float32)
    out = np.empty_like(arr)
    nbytes = lib.htpu_wire_roundtrip(
        wire_dtype.encode("utf-8"), arr.ctypes.data, arr.size,
        out.ctypes.data)
    if nbytes < 0:
        raise ValueError(f"unknown wire dtype: {wire_dtype!r}")
    return out, int(nbytes)


def wire_encode(wire_dtype: str, values) -> bytes:
    """Encode a float32 array into the ring's wire image
    (``EncodeWireChunk`` framing, per 64K-element sub-chunk).  Unit-test
    hook for cross-plane codec parity against the in-jit encoder."""
    import numpy as np
    lib = load()
    if lib is None or getattr(lib, "htpu_wire_encode", None) is None:
        raise RuntimeError("native core wire codec not available")
    arr = np.ascontiguousarray(values, dtype=np.float32).reshape(-1)
    total = lib.htpu_wire_bytes(wire_dtype.encode("utf-8"), arr.size)
    if total < 0:
        raise ValueError(f"unknown wire dtype: {wire_dtype!r}")
    out = np.empty(int(total), dtype=np.uint8)
    rc = lib.htpu_wire_encode(wire_dtype.encode("utf-8"), arr.ctypes.data,
                              arr.size, out.ctypes.data)
    if rc < 0:
        raise ValueError(f"wire encode failed for {wire_dtype!r}")
    return out.tobytes()


def wire_decode(wire_dtype: str, buf: bytes, n_elems: int):
    """Decode a wire image produced by :func:`wire_encode` (or by the
    in-jit encoder — that is the point) back to float32."""
    import numpy as np
    lib = load()
    if lib is None or getattr(lib, "htpu_wire_decode", None) is None:
        raise RuntimeError("native core wire codec not available")
    inp = np.frombuffer(buf, dtype=np.uint8)
    out = np.empty(n_elems, dtype=np.float32)
    rc = lib.htpu_wire_decode(wire_dtype.encode("utf-8"), inp.ctypes.data,
                              n_elems, out.ctypes.data)
    if rc < 0:
        raise ValueError(f"wire decode failed for {wire_dtype!r}")
    return out


def sum_into(dtype: str, acc, inp) -> None:
    """Native ``acc += inp`` elementwise (reduce.h SumInto) on two
    C-contiguous same-size numpy arrays; ``dtype`` is the htpu dtype name
    (may differ from the arrays' numpy dtype — e.g. "bfloat16" over uint16
    storage).  Unit-test hook for the parallel reduction path."""
    lib = load()
    if lib is None:
        raise RuntimeError("native core not available")
    if acc.nbytes != inp.nbytes:
        raise ValueError("size mismatch")
    rc = lib.htpu_sum_into(dtype.encode("utf-8"), acc.ctypes.data,
                           inp.ctypes.data, acc.nbytes)
    if rc != 0:
        raise ValueError(f"SumInto failed for dtype {dtype!r}")


def _parse_stall_records(data: bytes):
    """Decode the stall wire format (c_api.cc SerializeStallRecords):
    repeated { name_len:i32 name age:f64 n_missing:i32 ranks:i32[n] },
    little-endian.  Returns ``(name, age_s, missing_ranks)`` triples."""
    import struct
    result, pos = [], 0
    while pos < len(data):
        (nlen,) = struct.unpack_from("<i", data, pos)
        pos += 4
        name = data[pos:pos + nlen].decode("utf-8")
        pos += nlen
        (age,) = struct.unpack_from("<d", data, pos)
        pos += 8
        (nmiss,) = struct.unpack_from("<i", data, pos)
        pos += 4
        ranks = list(struct.unpack_from(f"<{nmiss}i", data, pos))
        pos += 4 * nmiss
        result.append((name, age, ranks))
    return result


def metrics_snapshot() -> dict:
    """JSON snapshot of the native metrics registry (cpp/htpu/metrics.h):
    ``{"counters": {...}, "gauges": {...}, "histograms": {...}}``.
    Empty dict when the native core is unavailable."""
    import json
    lib = load()
    if lib is None:
        return {}
    out = ctypes.c_void_p()
    n = lib.htpu_metrics_snapshot(ctypes.byref(out))
    if n < 0:
        return {}
    return json.loads(_take_buffer(lib, out, n).decode("utf-8"))


def metrics_reset() -> None:
    """Zero every native counter/gauge/histogram (tests, bench windows)."""
    lib = load()
    if lib is not None:
        lib.htpu_metrics_reset()


def agg_merge(a: bytes, b: bytes):
    """Fold serialized aggregation container ``b`` into ``a`` through the
    native merge (cpp/htpu/aggregate.cc) and return the canonical merged
    container bytes.  ``None`` when the native core is unavailable or
    predates the aggregation tier; raises ``ValueError`` on a corrupt
    container — the parity seam tests/test_aggregate.py drives against
    the Python mirror (horovod_tpu/aggregate.py)."""
    lib = load()
    if lib is None or not hasattr(lib, "htpu_agg_merge"):
        return None
    out = ctypes.c_void_p()
    n = lib.htpu_agg_merge(a, len(a), b, len(b), ctypes.byref(out))
    if n < 0:
        raise ValueError("corrupt aggregation container")
    return _take_buffer(lib, out, n)


def agg_roundtrip(buf: bytes):
    """Parse + canonically re-serialize one aggregation container through
    the native code.  ``None`` when the native core is unavailable or
    predates the aggregation tier; raises ``ValueError`` on a corrupt
    container."""
    lib = load()
    if lib is None or not hasattr(lib, "htpu_agg_roundtrip"):
        return None
    out = ctypes.c_void_p()
    n = lib.htpu_agg_roundtrip(buf, len(buf), ctypes.byref(out))
    if n < 0:
        raise ValueError("corrupt aggregation container")
    return _take_buffer(lib, out, n)


def observe_enabled():
    """Native observatory state: True/False, or ``None`` when the native
    core is unavailable or predates the observatory."""
    lib = load()
    if lib is None or not hasattr(lib, "htpu_observe_enabled"):
        return None
    return bool(lib.htpu_observe_enabled())


def observe_set_enabled(on: bool) -> None:
    """Flip the native observatory at runtime (bench A/B, tests)."""
    lib = load()
    if lib is not None and hasattr(lib, "htpu_observe_set_enabled"):
        lib.htpu_observe_set_enabled(1 if on else 0)


def observe_note_step(step_s: float, compute_s: float = 0.0,
                      hidden_s: float = 0.0, exposed_s: float = 0.0,
                      stall_s: float = 0.0) -> bool:
    """Feed one step's decomposition to the native observatory; returns
    False when the native core is unavailable (caller falls back to the
    Python registry)."""
    lib = load()
    if lib is None or not hasattr(lib, "htpu_observe_note_step"):
        return False
    lib.htpu_observe_note_step(step_s, compute_s, hidden_s, exposed_s,
                               stall_s)
    return True


def observe_snapshot() -> dict:
    """Local telemetry digest (step EWMAs, per-leg bandwidth EWMAs,
    inflight) as a dict; empty when the native core is unavailable."""
    import json
    lib = load()
    if lib is None or not hasattr(lib, "htpu_observe_snapshot"):
        return {}
    out = ctypes.c_void_p()
    n = lib.htpu_observe_snapshot(ctypes.byref(out))
    if n < 0:
        return {}
    return json.loads(_take_buffer(lib, out, n).decode("utf-8"))


def observe_reset() -> None:
    """Zero the native observatory EWMAs and counts (tests, bench A/B)."""
    lib = load()
    if lib is not None and hasattr(lib, "htpu_observe_reset"):
        lib.htpu_observe_reset()


def observe_record_xfer(leg: int, sent_bytes: int, recv_bytes: int,
                        seconds: float) -> None:
    """Test seam: record one transfer on leg 0..3 (classic/shm/uring/
    ctrl) without driving a real job."""
    lib = load()
    if lib is not None and hasattr(lib, "htpu_observe_record_xfer"):
        lib.htpu_observe_record_xfer(leg, sent_bytes, recv_bytes, seconds)


def observe_trailer_encode() -> bytes:
    """The telemetry trailer this process would append to its next tick
    frame — b"" when the observatory is off (golden-frame contract)."""
    lib = load()
    if lib is None or not hasattr(lib, "htpu_observe_trailer_encode"):
        return b""
    out = ctypes.c_void_p()
    n = lib.htpu_observe_trailer_encode(ctypes.byref(out))
    if n <= 0:
        return b""
    return _take_buffer(lib, out, n)


def observe_trailer_probe(blob: bytes) -> dict:
    """Strip-probe arbitrary frame bytes the way the coordinator does:
    ``{"stripped": bool, "payload_len": int, "sample": {...}}``; empty
    dict when the native core is unavailable."""
    import json
    lib = load()
    if lib is None or not hasattr(lib, "htpu_observe_trailer_probe"):
        return {}
    out = ctypes.c_void_p()
    n = lib.htpu_observe_trailer_probe(blob, len(blob), ctypes.byref(out))
    if n < 0:
        return {}
    return json.loads(_take_buffer(lib, out, n).decode("utf-8"))


def crc32c_native(data: bytes):
    """CRC32C (Castagnoli) via the native runtime-dispatched path (SSE4.2
    when available); ``None`` when the native core is unavailable or
    predates the integrity layer — callers fall back to the pure-Python
    table in horovod_tpu.wire."""
    lib = load()
    if lib is None or not hasattr(lib, "htpu_crc32c"):
        return None
    return int(lib.htpu_crc32c(data, len(data)))


def crc32c_native_sw(data: bytes):
    """The native software (table) path, regardless of CPU support — for
    pinning hardware == software == Python on the same inputs."""
    lib = load()
    if lib is None or not hasattr(lib, "htpu_crc32c_sw"):
        return None
    return int(lib.htpu_crc32c_sw(data, len(data)))


def crc32c_hardware() -> bool:
    """True when the native dispatcher selected the SSE4.2 path."""
    lib = load()
    if lib is None or not hasattr(lib, "htpu_crc32c_hw"):
        return False
    return bool(lib.htpu_crc32c_hw())


class CppControlPlane:
    """Multi-process control + eager data plane (TCP, native).

    Replaces the reference's MPI gather/bcast negotiation and CPU MPI data
    plane (``operations.cc:1665-1903, 1232-1353``).  Process 0 is the
    coordinator; construction blocks until the whole job is connected.
    """

    def __init__(self, process_index: int, process_count: int, host: str,
                 port: int, first_rank: int, nranks_total: int,
                 timeout_ms: int = 60000):
        self._lib = load()
        if self._lib is None:
            raise RuntimeError("native core not available")
        # Serializes destruction against an attached timeline's __del__
        # detach (CppTimeline.__del__): without it the detach could call
        # into a plane freed between its pointer snapshot and the ctypes
        # call.
        self._teardown_lock = threading.Lock()
        self._ptr = self._lib.htpu_control_create(
            process_index, process_count, host.encode("utf-8"), port,
            first_rank, nranks_total, timeout_ms)
        if not self._ptr:
            raise ConnectionError(
                f"control plane failed to form (coordinator {host}:{port}, "
                f"process {process_index}/{process_count})")

    def tick(self, request_list_blob: bytes,
             fusion_threshold: int) -> bytes:
        out = ctypes.c_void_p()
        n = self._lib.htpu_control_tick(
            self._ptr, request_list_blob, len(request_list_blob),
            fusion_threshold, ctypes.byref(out))
        if n < 0:
            raise ConnectionError("control-plane tick failed")
        return _take_buffer(self._lib, out, n)

    def allreduce(self, dtype: str, data, wire_dtype: str = "",
                  algo: str = "") -> bytes:
        """Allreduce ``data`` (bytes, or a C-contiguous numpy array —
        arrays are read straight from their buffer, skipping a
        ``tobytes`` copy; the payload path is copy-bound at multi-MB
        gradients).  ``wire_dtype`` selects the ring wire compression
        ("" = raw; "bf16"/"fp16"/"int8", float32 payloads only — see
        cpp/htpu/quantize.h).  ``algo`` is the coordinator-resolved
        collective algorithm ("" = flat ring; "hier" = two-level
        hierarchical; "small" = latency-optimal small-tensor path —
        cpp/htpu/control.h)."""
        import numpy as np
        if isinstance(data, np.ndarray):
            if not data.flags["C_CONTIGUOUS"]:
                data = np.ascontiguousarray(data)
            ptr, length = data.ctypes.data, data.nbytes
        else:
            ptr, length = data, len(data)
        out = ctypes.c_void_p()
        n = self._lib.htpu_control_allreduce_algo(
            self._ptr, dtype.encode("utf-8"), wire_dtype.encode("utf-8"),
            algo.encode("utf-8"), ptr, length, ctypes.byref(out))
        if n < 0:
            raise ConnectionError(
                "data-plane allreduce failed"
                + (f" (wire dtype {wire_dtype!r})" if wire_dtype else "")
                + (f" (algo {algo!r})" if algo else ""))
        return _take_buffer(self._lib, out, n)

    def allgather(self, data: bytes) -> bytes:
        out = ctypes.c_void_p()
        n = self._lib.htpu_control_allgather(
            self._ptr, data, len(data), ctypes.byref(out))
        if n < 0:
            raise ConnectionError("data-plane allgather failed")
        return _take_buffer(self._lib, out, n)

    def broadcast(self, root_process: int, data: bytes) -> bytes:
        out = ctypes.c_void_p()
        n = self._lib.htpu_control_broadcast(
            self._ptr, root_process, data, len(data), ctypes.byref(out))
        if n < 0:
            raise ConnectionError("data-plane broadcast failed")
        return _take_buffer(self._lib, out, n)

    def data_bytes(self):
        """(sent, received) cumulative eager data-plane payload bytes of
        this process — the ring keeps both O(payload) per collective
        regardless of process count."""
        sent = ctypes.c_longlong()
        recvd = ctypes.c_longlong()
        self._lib.htpu_control_data_bytes(self._ptr, ctypes.byref(sent),
                                          ctypes.byref(recvd))
        return sent.value, recvd.value

    def ring_transport(self) -> str:
        """'uds' when the ring-next hop rides a Unix domain socket (the
        co-located on-host fast path), 'tcp' across hosts, 'none' when
        single-process."""
        return self._lib.htpu_control_ring_transport(
            self._ptr).decode("ascii")

    def data_transport(self) -> str:
        """Zero-copy transports active on the data plane: 'classic',
        'shm', 'uring', or 'shm+uring' (HOROVOD_TPU_TRANSPORT and any
        runtime fallbacks both reflected)."""
        return self._lib.htpu_control_data_transport(
            self._ptr).decode("ascii")

    def stalled(self, age_s: float):
        out = ctypes.c_void_p()
        n = self._lib.htpu_control_stalled(self._ptr, age_s,
                                           ctypes.byref(out))
        return _parse_stall_records(_take_buffer(self._lib, out, n))

    def membership(self):
        """Current elastic membership identity of this process:
        ``(process_index, process_count, first_rank, generation)``.  All
        four change together on a RECONFIGURE — re-read after any tick
        whose response carried a reconfigure payload.  Generation is 0
        (and the rest Create-time constants) on non-elastic planes or an
        older native core."""
        if not hasattr(self._lib, "htpu_control_membership"):
            return -1, -1, -1, 0
        pi = ctypes.c_int()
        pc = ctypes.c_int()
        fr = ctypes.c_int()
        gen = ctypes.c_int()
        self._lib.htpu_control_membership(
            self._ptr, ctypes.byref(pi), ctypes.byref(pc), ctypes.byref(fr),
            ctypes.byref(gen))
        return pi.value, pc.value, fr.value, gen.value

    def elastic(self) -> bool:
        """True when HOROVOD_TPU_ELASTIC=1 was honoured by this plane."""
        if not hasattr(self._lib, "htpu_control_elastic"):
            return False
        return bool(self._lib.htpu_control_elastic(self._ptr))

    def set_xfer_context(self, tensors: str) -> None:
        """Name the tensors of the collective about to run; a checked
        transfer that exhausts its retransmit budget folds this into the
        attributed error (HOROVOD_TPU_INTEGRITY).  No-op on an older
        native core."""
        if hasattr(self._lib, "htpu_control_set_xfer_context"):
            self._lib.htpu_control_set_xfer_context(
                self._ptr, tensors.encode("utf-8", "replace"))

    def last_error(self):
        """Attribution of the most recent native failure on this process:
        ``(failed_first_rank, reason)`` — rank is -1 when nothing failed.
        Read after a ConnectionError from the data plane to build the
        worker's abort report."""
        rank = ctypes.c_int(-1)
        out = ctypes.c_void_p()
        n = self._lib.htpu_control_last_error(self._ptr, ctypes.byref(rank),
                                              ctypes.byref(out))
        reason = _take_buffer(self._lib, out, n).decode("utf-8", "replace")
        return rank.value, reason

    def close(self):
        if getattr(self, "_leaked", False):
            return   # pointer stays valid for the wedged thread; no free
        with self._teardown_lock:
            ptr, self._ptr = self._ptr, None
            if ptr:
                self._lib.htpu_control_destroy(ptr)

    def leak(self):
        """Disarm destruction WITHOUT invalidating the pointer — for
        shutdown with a wedged background thread still inside (or about
        to make) a control-plane call: destroying would be a
        use-after-free, and nulling the pointer would turn the thread's
        next ctypes call into a NULL dereference in C++.  The object is
        reclaimed by process exit."""
        self._leaked = True

    def __del__(self):
        try:
            self.close()
        except Exception:   # noqa: BLE001 — interpreter teardown
            pass


class CppTimeline:
    """Native Chrome-trace writer with the interface of
    :class:`horovod_tpu.timeline.Timeline`.

    Every method tolerates a closed timeline (no-op) — the executor may race
    a late span against ``Controller.stop()``'s close, and calling into C++
    with a destroyed object would crash the interpreter where the Python
    fallback merely raises.
    """

    def __init__(self, path: str, rank: int = 0):
        self._lib = load()
        if self._lib is None:
            raise RuntimeError("native core not available")
        if hasattr(self._lib, "htpu_timeline_create_rank"):
            self._ptr = self._lib.htpu_timeline_create_rank(
                path.encode("utf-8"), int(rank))
        else:   # stale prebuilt .so: trace_t0 reports rank 0
            self._ptr = self._lib.htpu_timeline_create(path.encode("utf-8"))
        if not self._ptr:
            raise OSError(f"cannot open timeline file: {path}")
        self.rank = rank

    def attach_to_control(self, control: "CppControlPlane") -> None:
        """Wire this writer into the native coordinator so its Tick loop
        emits NEGOTIATE_* spans (multi-process mode negotiates in C++,
        bypassing the Python MessageTable's timeline hooks).  Lifetime:
        the Controller closes the control plane before this timeline; for
        teardown paths that skip the Controller (no hvd.shutdown), the
        weakref lets ``__del__`` detach instead of destroying under the
        coordinator's raw pointer."""
        if self._ptr and control._ptr:
            import weakref
            self._lib.htpu_control_set_timeline(control._ptr, self._ptr)
            self._control_ref = weakref.ref(control)

    def negotiate_start(self, tensor_name: str, request_type) -> None:
        if not self._ptr:
            return
        self._lib.htpu_timeline_negotiate_start(
            self._ptr, tensor_name.encode("utf-8"), int(request_type))

    def negotiate_rank_ready(self, tensor_name: str, rank: int) -> None:
        if not self._ptr:
            return
        self._lib.htpu_timeline_negotiate_rank_ready(
            self._ptr, tensor_name.encode("utf-8"), rank)

    def negotiate_end(self, tensor_name: str) -> None:
        if not self._ptr:
            return
        self._lib.htpu_timeline_negotiate_end(
            self._ptr, tensor_name.encode("utf-8"))

    def start(self, tensor_name: str, response_type) -> None:
        if not self._ptr:
            return
        self._lib.htpu_timeline_start(
            self._ptr, tensor_name.encode("utf-8"), int(response_type))

    def end(self, tensor_name: str) -> None:
        if not self._ptr:
            return
        self._lib.htpu_timeline_end(self._ptr, tensor_name.encode("utf-8"))

    def activity_start_all(self, entries, activity: str) -> None:
        if not self._ptr:
            return
        for e in entries:
            self._lib.htpu_timeline_activity_start(
                self._ptr, e.name.encode("utf-8"), activity.encode("utf-8"))

    def activity_end_all(self, entries) -> None:
        if not self._ptr:
            return
        for e in entries:
            self._lib.htpu_timeline_activity_end(
                self._ptr, e.name.encode("utf-8"))

    def counter(self, name: str, value: int) -> None:
        """Chrome-trace counter sample ("ph": "C") — queue depth, bytes in
        flight — rendered by Perfetto as a rate track."""
        if not self._ptr:
            return
        self._lib.htpu_timeline_counter(
            self._ptr, name.encode("utf-8"), int(value))

    def cache_hit_tick(self, dur_us: int) -> None:
        """CACHED_TICK complete-event span — a negotiation tick served
        entirely from the response cache."""
        if not self._ptr:
            return
        self._lib.htpu_timeline_cache_hit_tick(self._ptr, int(dur_us))

    def tick_span(self, tick: int, dur_us: int) -> None:
        """TICK complete-event span tagged with the tick id — the
        cross-rank alignment anchor trace_merge.py lines traces up by."""
        if not self._ptr or not hasattr(self._lib,
                                        "htpu_timeline_tick_span"):
            return
        self._lib.htpu_timeline_tick_span(self._ptr, int(tick), int(dur_us))

    def instant(self, name: str, args: dict = None) -> None:
        """Global instant event on the control track."""
        if not self._ptr or not hasattr(self._lib, "htpu_timeline_instant"):
            return
        import json
        self._lib.htpu_timeline_instant(
            self._ptr, name.encode("utf-8"),
            json.dumps(args or {}).encode("utf-8"))

    def flush(self) -> None:
        if self._ptr:
            self._lib.htpu_timeline_flush(self._ptr)

    def leak(self):
        """Abandon the native writer WITHOUT destroying it — for shutdown
        with a wedged background thread whose control plane still holds
        the raw Timeline pointer (see Controller.stop).  The file is
        finalized best-effort: ``htpu_timeline_close`` only closes the
        stream under the object's own mutex and every later write no-ops,
        so the wedged thread can still call through its stale pointer
        safely — only ``htpu_timeline_destroy`` is the use-after-free
        hazard, and that never runs for a leaked writer (``__del__`` sees
        a null ``_ptr``).  The close runs on a bounded-wait daemon
        thread: in the usual wedge (thread stuck in a control-plane recv)
        the timeline mutex is free and it finishes instantly, but a
        writer wedged INSIDE ``Emit`` (full disk, hung NFS) holds that
        mutex, and leak() must never convert a 90 s join timeout into an
        unbounded hang of shutdown itself."""
        ptr, self._ptr = self._ptr, None
        if ptr:
            import threading

            def _close():
                try:
                    self._lib.htpu_timeline_close(ptr)
                except Exception:   # noqa: BLE001 — best-effort finalize
                    pass

            t = threading.Thread(target=_close, daemon=True,
                                 name="htpu-timeline-leak-close")
            t.start()
            t.join(timeout=2.0)

    def close(self):
        # Close only finalizes the file; the C++ object stays alive (its
        # methods no-op once closed, under its own mutex) so a racing span
        # from the executor can never hit freed memory.  The object itself
        # is destroyed when this wrapper is garbage collected.
        if self._ptr:
            self._lib.htpu_timeline_close(self._ptr)

    def __del__(self):
        try:
            ptr, self._ptr = self._ptr, None
            if not ptr:
                return
            self._lib.htpu_timeline_close(ptr)
            ctrl = (self._control_ref()
                    if hasattr(self, "_control_ref") else None)
            if ctrl is not None:
                # Interpreter teardown without hvd.shutdown(): the native
                # coordinator may still hold this raw pointer while its
                # tick caller (a daemon thread) is mid-call.  Under the
                # plane's teardown lock — so a concurrent close() cannot
                # destroy the plane between the pointer read and the
                # call — detach so new ticks see no timeline, and LEAK
                # the object instead of destroying under a
                # possibly-in-flight span: a stale pointer into the
                # closed-but-alive writer is a locked no-op, a destroyed
                # one is a use-after-free.  Bounded acquire: this
                # finalizer can run via cyclic GC ON the thread currently
                # holding the lock inside close() — a blocking acquire
                # there would deadlock the interpreter; on timeout, leak
                # the writer without detaching (still safe: close() only
                # destroys the PLANE, and this writer is never destroyed).
                if not ctrl._teardown_lock.acquire(timeout=2.0):
                    return
                try:
                    ctrl_ptr = getattr(ctrl, "_ptr", None)
                    if ctrl_ptr:
                        self._lib.htpu_control_set_timeline(ctrl_ptr, None)
                        return
                finally:
                    ctrl._teardown_lock.release()
                # Plane already closed: nothing references the writer any
                # more — destroying it below is safe.
            self._lib.htpu_timeline_destroy(ptr)
        except Exception:   # noqa: BLE001 — interpreter teardown
            pass
