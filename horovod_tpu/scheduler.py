"""Plane-agnostic collective scheduler (Python mirror of cpp/htpu/scheduler).

One policy, two planes: the eager TCP ring and the in-jit shard_map path
both take their fusion grouping, bucket issue order, and allreduce
algorithm choice from this module.  The native implementation in
``cpp/htpu/scheduler.cc`` is preferred when the core library is loaded;
the pure-Python classes here are the bit-for-bit fallback and the
reference for parity tests.

Issue-order policy: **first-ready-first-issued** — a bucket's collective
launches the moment its last gradient materializes, which is what lets
backward-overlap hide communication under the remaining backprop.  On the
eager plane the negotiated ResponseList already carries that order (the
coordinator pops tensors in readiness order), so cached ticks replay the
schedule verbatim.  On the in-jit plane :func:`issue_order` stages bucket
collectives in reverse registration order — the backward pass produces
the last layer's gradients first, so reversed declaration order is the
static approximation of readiness order inside one XLA program.

Knobs:

- ``HOROVOD_TPU_OVERLAP``: enable backward-overlap on both planes
  (default off — reductions launch after backward completes, the
  pre-scheduler behavior).
- ``HOROVOD_TPU_BUCKET_BYTES``: overlap bucket byte bound (default
  67108864, matching the fusion threshold).  A leaf larger than the
  bound always rides alone.
"""

from __future__ import annotations

import os
from typing import Callable, List, Optional, Sequence

from . import cpp_core

DEFAULT_BUCKET_BYTES = 64 * 1024 * 1024


def overlap_enabled(override: Optional[bool] = None) -> bool:
    """Resolve the overlap switch: explicit argument wins, else the
    ``HOROVOD_TPU_OVERLAP`` knob, else off."""
    if override is not None:
        return bool(override)
    return os.environ.get("HOROVOD_TPU_OVERLAP", "").lower() in (
        "1", "true", "yes", "on")


def bucket_bytes_from_env(override: Optional[int] = None) -> int:
    """Resolve the overlap bucket bound: explicit argument wins, else the
    ``HOROVOD_TPU_BUCKET_BYTES`` knob, else 64 MiB."""
    if override is not None:
        return int(override)
    raw = os.environ.get("HOROVOD_TPU_BUCKET_BYTES", "")
    try:
        v = int(raw)
        return v if v > 0 else DEFAULT_BUCKET_BYTES
    except ValueError:
        return DEFAULT_BUCKET_BYTES


def resolve_algo(pref: str, nbytes: int, num_hosts: int = 1,
                 num_procs: int = 1,
                 crossover_bytes: Optional[int] = None) -> str:
    """Map an algorithm preference to the data-plane algorithm ("" = flat
    ring).  Mirrors ``htpu::ResolveAlgo`` exactly; parity is tested."""
    from .core import DEFAULT_ALGO_CROSSOVER_BYTES
    if crossover_bytes is None:
        crossover_bytes = DEFAULT_ALGO_CROSSOVER_BYTES
    if pref in ("", "ring"):
        return ""
    if pref != "auto":
        return pref
    if nbytes <= crossover_bytes:
        return "small"
    if 1 < num_hosts < num_procs:
        return "hier"
    return ""


def plan_tick(responses, entry_bytes, entry_dtype, threshold):
    """Full per-tick policy: fusion plus first-ready-first-issued order.

    The input arrives in negotiation-readiness order and fusion's stable
    left-to-right merge preserves it, so the returned list IS the issue
    schedule — the response cache stores and replays it verbatim.
    """
    from .core import plan_fusion
    return plan_fusion(responses, entry_bytes, entry_dtype, threshold)


def pack_buckets(sizes: Sequence[int], dtypes: Sequence[str],
                 bucket_bytes: int) -> List[List[int]]:
    """Pack leaves (declaration order) into byte-bounded buckets.

    Consecutive leaves with the same dtype share a bucket while the total
    stays within ``bucket_bytes``.  A leaf larger than ``bucket_bytes``
    rides alone: it opens a fresh bucket that is immediately closed, so
    later leaves can never join past the byte bound.
    """
    buckets: List[List[int]] = []
    open_idx = -1
    open_bytes = 0
    open_dtype = None
    for i, (nbytes, dtype) in enumerate(zip(sizes, dtypes)):
        oversized = nbytes > bucket_bytes
        joins = (open_idx >= 0 and not oversized and dtype == open_dtype
                 and open_bytes + nbytes <= bucket_bytes)
        if not joins:
            buckets.append([])
            open_idx = len(buckets) - 1
            open_bytes = 0
            open_dtype = dtype
        buckets[open_idx].append(i)
        open_bytes += nbytes
        if oversized:
            open_idx = -1
    return buckets


def issue_order(num_buckets: int, overlap: bool) -> List[int]:
    """Static issue order for the in-jit plane: reversed registration
    order under overlap (backward materializes the last bucket first),
    declaration order otherwise."""
    order = list(range(num_buckets))
    return order[::-1] if overlap else order


class PyBucketPlanner:
    """Pure-Python backward-overlap bucket planner; same surface and
    semantics as ``htpu::BucketPlanner`` / ``cpp_core.NativeBucketPlanner``."""

    def __init__(self, bucket_bytes: int):
        self._bucket_bytes = (int(bucket_bytes) if bucket_bytes > 0
                              else DEFAULT_BUCKET_BYTES)
        self._sealed = False
        self._names: List[str] = []
        self._sizes: List[int] = []
        self._dtypes: List[str] = []
        self._bucket_of: List[int] = []
        self._buckets: List[List[int]] = []
        self._leaf_ready: List[bool] = []
        self._ready_count: List[int] = []
        self._issued: List[bool] = []
        self._complete: List[bool] = []
        self._issue_queue: List[int] = []
        self._issue_head = 0

    def close(self) -> None:
        pass

    def register_leaf(self, name: str, nbytes: int, dtype: str) -> int:
        if self._sealed:
            return -1
        self._names.append(name)
        self._sizes.append(int(nbytes))
        self._dtypes.append(dtype)
        return len(self._names) - 1

    def seal(self) -> int:
        if self._sealed:
            return len(self._buckets)
        self._sealed = True
        self._buckets = pack_buckets(self._sizes, self._dtypes,
                                     self._bucket_bytes)
        self._bucket_of = [-1] * len(self._names)
        for b, leaves in enumerate(self._buckets):
            for leaf in leaves:
                self._bucket_of[leaf] = b
        n = len(self._buckets)
        self._leaf_ready = [False] * len(self._names)
        self._ready_count = [0] * n
        self._issued = [False] * n
        self._complete = [False] * n
        from .metrics import registry
        registry.inc("overlap.buckets", n)
        return n

    def num_buckets(self) -> int:
        return len(self._buckets)

    def bucket_of(self, leaf: int) -> int:
        if leaf < 0 or leaf >= len(self._bucket_of):
            return -1
        return self._bucket_of[leaf]

    def bucket_leaves(self, bucket: int) -> List[int]:
        return list(self._buckets[bucket])

    def bucket_bytes(self, bucket: int) -> int:
        if bucket < 0 or bucket >= len(self._buckets):
            return -1
        return sum(self._sizes[i] for i in self._buckets[bucket])

    def note_ready(self, leaf: int) -> int:
        if not self._sealed or leaf < 0 or leaf >= len(self._names):
            return -1
        if self._leaf_ready[leaf]:
            return -1
        self._leaf_ready[leaf] = True
        b = self._bucket_of[leaf]
        self._ready_count[b] += 1
        if self._ready_count[b] < len(self._buckets[b]):
            return -1
        self._issue_queue.append(b)
        return b

    def next_issue(self) -> int:
        while self._issue_head < len(self._issue_queue):
            b = self._issue_queue[self._issue_head]
            self._issue_head += 1
            if self._issued[b]:
                continue
            self._issued[b] = True
            cpp_core.flight_record("bucket.issue", "", self.bucket_bytes(b),
                                   b, len(self._buckets[b]))
            return b
        return -1

    def note_complete(self, bucket: int) -> None:
        if bucket < 0 or bucket >= len(self._buckets):
            return
        if self._complete[bucket]:
            return
        self._complete[bucket] = True
        cpp_core.flight_record("bucket.complete", "",
                               self.bucket_bytes(bucket), bucket,
                               len(self._buckets[bucket]))

    def all_complete(self) -> bool:
        return self._sealed and all(self._complete)

    def reset(self) -> None:
        self._leaf_ready = [False] * len(self._names)
        self._ready_count = [0] * len(self._buckets)
        self._issued = [False] * len(self._buckets)
        self._complete = [False] * len(self._buckets)
        self._issue_queue = []
        self._issue_head = 0


def make_bucket_planner(bucket_bytes: int, prefer_native: bool = True):
    """A bucket planner: the native C++ one when the core library exports
    the scheduler API, else the pure-Python mirror."""
    if prefer_native:
        try:
            return cpp_core.NativeBucketPlanner(bucket_bytes)
        except (RuntimeError, OSError):
            pass
    return PyBucketPlanner(bucket_bytes)
