"""Gradient compression — parity with the reference's Compression classes
(``horovod/tensorflow/compression.py``, ``horovod/torch/compression.py``).

The reference casts gradients to fp16 before the allreduce and back after.
On TPU the natural compressed wire type is **bfloat16** (native MXU/ICI
type, same dynamic range as fp32), so ``Compression.fp16`` keeps the
reference's name/behaviour while ``Compression.bf16`` is the TPU-preferred
choice.  Works on single arrays or pytrees, inside or outside jit.
"""

from __future__ import annotations

import jax.numpy as jnp


class Compressor:
    """Interface: ``compress`` returns (compressed, ctx); ``decompress``
    restores (reference ``compression.py:23-44``)."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype = None

    @classmethod
    def compress(cls, tensor):
        dtype = jnp.result_type(tensor)
        if jnp.issubdtype(dtype, jnp.floating) and dtype != cls.wire_dtype:
            return tensor.astype(cls.wire_dtype), dtype
        return tensor, None

    @classmethod
    def decompress(cls, tensor, ctx):
        return tensor.astype(ctx) if ctx is not None else tensor


class FP16Compressor(_CastCompressor):
    wire_dtype = jnp.float16


class BF16Compressor(_CastCompressor):
    wire_dtype = jnp.bfloat16


class Compression:
    """Namespace parity with ``hvd.Compression`` (reference
    ``compression.py:62-75``)."""
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
