"""Gradient compression — parity with the reference's Compression classes
(``horovod/tensorflow/compression.py``, ``horovod/torch/compression.py``).

The reference casts gradients to fp16 before the allreduce and back after.
On TPU the natural compressed wire type is **bfloat16** (native MXU/ICI
type, same dynamic range as fp32), so ``Compression.fp16`` keeps the
reference's name/behaviour while ``Compression.bf16`` is the TPU-preferred
choice.  Works on single arrays or pytrees, inside or outside jit.
"""

from __future__ import annotations

import jax.numpy as jnp


class Compressor:
    """Interface: ``compress`` returns (compressed, ctx); ``decompress``
    restores (reference ``compression.py:23-44``)."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype = None

    @classmethod
    def compress(cls, tensor):
        dtype = jnp.result_type(tensor)
        if jnp.issubdtype(dtype, jnp.floating) and dtype != cls.wire_dtype:
            return tensor.astype(cls.wire_dtype), dtype
        return tensor, None

    @classmethod
    def decompress(cls, tensor, ctx):
        return tensor.astype(ctx) if ctx is not None else tensor


class FP16Compressor(_CastCompressor):
    wire_dtype = jnp.float16


class BF16Compressor(_CastCompressor):
    wire_dtype = jnp.bfloat16


class Int8Compressor(Compressor):
    """Per-block absmax int8 quantization (EQuARX-style: 1024-element
    blocks, fp32 scales — same grid as the host ring's int8 wire,
    ``cpp/htpu/quantize.cc``).

    On the mesh path the quantized values cannot ride a ``psum`` as raw
    int8 (sums overflow, and per-block scales don't commute with the
    reduction), so ``compress`` snaps the tensor onto the int8 grid and
    returns it **dequantized in bfloat16**: a single sum-safe array that
    still halves the bytes on the wire.  True 4x int8 bytes-on-wire
    lives on the cross-process host ring — request it with
    ``allreduce(..., compression=Compression.int8)`` or process-wide via
    ``HOROVOD_TPU_WIRE_DTYPE=int8``.
    """

    block_elems = 1024

    @classmethod
    def compress(cls, tensor):
        dtype = jnp.result_type(tensor)
        if not jnp.issubdtype(dtype, jnp.floating):
            return tensor, None
        n = tensor.size
        blocks = -(-n // cls.block_elems)
        flat = jnp.ravel(tensor).astype(jnp.float32)
        padded = jnp.pad(flat, (0, blocks * cls.block_elems - n))
        grid = padded.reshape(blocks, cls.block_elems)
        absmax = jnp.max(jnp.abs(grid), axis=1, keepdims=True)
        scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
        q = jnp.clip(jnp.round(grid / scale), -127, 127)
        deq = (q * scale).reshape(-1)[:n].reshape(tensor.shape)
        return deq.astype(jnp.bfloat16), dtype

    @classmethod
    def decompress(cls, tensor, ctx):
        return tensor.astype(ctx) if ctx is not None else tensor


class Compression:
    """Namespace parity with ``hvd.Compression`` (reference
    ``compression.py:62-75``)."""
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
    int8 = Int8Compressor
