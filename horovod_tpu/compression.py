"""Gradient compression — parity with the reference's Compression classes
(``horovod/tensorflow/compression.py``, ``horovod/torch/compression.py``).

The reference casts gradients to fp16 before the allreduce and back after.
On TPU the natural compressed wire type is **bfloat16** (native MXU/ICI
type, same dynamic range as fp32), so ``Compression.fp16`` keeps the
reference's name/behaviour while ``Compression.bf16`` is the TPU-preferred
choice.  Works on single arrays or pytrees, inside or outside jit.
"""

from __future__ import annotations

import jax.numpy as jnp


class Compressor:
    """Interface: ``compress`` returns (compressed, ctx); ``decompress``
    restores (reference ``compression.py:23-44``)."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype = None

    @classmethod
    def compress(cls, tensor):
        dtype = jnp.result_type(tensor)
        if jnp.issubdtype(dtype, jnp.floating) and dtype != cls.wire_dtype:
            return tensor.astype(cls.wire_dtype), dtype
        return tensor, None

    @classmethod
    def decompress(cls, tensor, ctx):
        return tensor.astype(ctx) if ctx is not None else tensor


class FP16Compressor(_CastCompressor):
    wire_dtype = jnp.float16


class BF16Compressor(_CastCompressor):
    wire_dtype = jnp.bfloat16


class Int8Compressor(Compressor):
    """Per-block absmax int8 quantization (EQuARX-style: 1024-element
    blocks, fp32 scales — same grid as the host ring's int8 wire,
    ``cpp/htpu/quantize.cc``).

    Where a true int8 wire exists, selecting this compressor engages it:
    the cross-process host ring (``allreduce(...,
    compression=Compression.int8)`` / ``HOROVOD_TPU_WIRE_DTYPE=int8``)
    and, inside ``shard_map`` on a flat mesh, the in-jit quantized ring
    (:func:`horovod_tpu.ops.quantized_collectives
    .quantized_ring_allreduce` — routed by ``reduce_gradients`` /
    ``allreduce_gradients`` per the bucket policy).  Everywhere else —
    e.g. the hierarchical mesh, whose three-stage collective cannot
    carry per-block scales — ``compress`` degrades gracefully: it snaps
    the tensor onto the int8 grid and returns it **dequantized in
    bfloat16**, a single sum-safe array that still halves the bytes on
    the wire.

    The block grid and scale rule are shared with both int8 wires
    (``quantized_collectives.snap_to_grid``), including the FLT_MIN
    scale clamp that keeps near-zero blocks NaN-free.
    """

    block_elems = 1024

    @classmethod
    def compress(cls, tensor):
        dtype = jnp.result_type(tensor)
        if not jnp.issubdtype(dtype, jnp.floating):
            return tensor, None
        from horovod_tpu.ops.quantized_collectives import snap_to_grid
        deq = snap_to_grid(tensor)
        return deq.astype(jnp.bfloat16), dtype

    @classmethod
    def decompress(cls, tensor, ctx):
        return tensor.astype(ctx) if ctx is not None else tensor


class Compression:
    """Namespace parity with ``hvd.Compression`` (reference
    ``compression.py:62-75``)."""
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
    int8 = Int8Compressor


# Canonical wire-compression names ("" = raw fp32) shared by BOTH planes:
# the eager ring's ``HOROVOD_TPU_WIRE_DTYPE`` / ``compression=`` strings
# and the in-jit ``HOROVOD_TPU_INJIT_WIRE_DTYPE`` / ``compression=``
# strings resolve through this one table, so a name accepted on one plane
# is accepted (with the same meaning and the same rejection message) on
# the other.  Matches WireDtypeId in cpp/htpu/quantize.cc.
WIRE_DTYPE_ALIASES = {
    "": "", "fp32": "", "float32": "", "none": "",
    "bf16": "bf16", "bfloat16": "bf16",
    "fp16": "fp16", "float16": "fp16",
    "int8": "int8",
}


def canonical_wire_dtype(name, source: str = "wire dtype") -> str:
    """Canonicalize a wire-compression name to ""/"bf16"/"fp16"/"int8".

    ``source`` names the knob being parsed (e.g. ``"compression"`` or an
    env var) so both planes reject unknown names with the identical
    message shape: ``{source}={name!r}: expected none|fp32|bf16|fp16|int8``.
    """
    key = (name or "").strip().lower()
    if key not in WIRE_DTYPE_ALIASES:
        raise ValueError(
            f"{source}={name!r}: expected none|fp32|bf16|fp16|int8")
    return WIRE_DTYPE_ALIASES[key]


def compressor_for_wire(wire: str):
    """The Compressor implementing a canonical wire name (inverse of the
    per-class ``wire_dtype`` mapping the eager plane stamps into
    requests)."""
    try:
        return {
            "": NoneCompressor,
            "bf16": BF16Compressor,
            "fp16": FP16Compressor,
            "int8": Int8Compressor,
        }[wire]
    except KeyError:
        raise ValueError(
            f"compressor_for_wire({wire!r}): not a canonical wire dtype "
            "(expected ''|bf16|fp16|int8)") from None
