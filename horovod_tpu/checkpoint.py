"""Checkpoint / resume utilities.

The reference has no core checkpoint subsystem; it establishes three
conventions the examples implement (SURVEY §5.4):

1. **rank-0-only writing** (``README.md`` step 6,
   ``examples/tensorflow_mnist_estimator.py:147``),
2. **resume = rank-0 restore + broadcast to all ranks** including the resume
   epoch (``examples/keras_imagenet_resnet50.py:64-103``), and
3. **optimizer-state rewrapping on load** (``hvd.load_model``,
   ``horovod/keras/__init__.py:115-148``; ``broadcast_optimizer_state`` for
   torch).

This module packages those conventions TPU-natively on orbax (the JAX
checkpointing library): save is a no-op off rank 0; restore happens on rank
0 and is broadcast through the framework's collective path so every rank
resumes bit-identical state.
"""

from __future__ import annotations

import dataclasses
import importlib
import json
import os
import re
import shutil
import sys
from typing import Any, Dict, List, Optional, Tuple

from horovod_tpu import basics


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.PyTreeCheckpointer()


def checkpoint_path(directory: str, epoch: int) -> str:
    # orbax requires absolute paths; accept relative ones at this API.
    return os.path.join(os.path.abspath(directory), f"checkpoint-{epoch}")


# ------------------------------------------------------------ delta chains
# The async snapshot stream (ckpt_stream.py) commits epochs as CHAIN
# directories instead of orbax trees: a committed ``checkpoint-N`` holding
# ``chain.json`` (manifest) and ``shards.npz`` (only the leaves whose bytes
# changed since the previous committed epoch).  A chain epoch is readable
# iff the manifest links ``prev`` hops back to a ``base`` epoch that still
# exists — :func:`chain_links` walks that list, and :func:`latest_epoch`
# only reports epochs whose full chain is intact, so a resume racing a
# crashed or garbage-collected writer falls back to the previous committed
# chain instead of picking a torn tip.

CHAIN_MANIFEST = "chain.json"
CHAIN_SHARDS = "shards.npz"

# Staging paths owned by a LIVE async writer, keyed by epoch: a concurrent
# synchronous save()'s _clean_stale must not reap an in-flight commit (the
# pre-chain cleaner could assume "no save running" because the single
# writer was the caller itself).
_ACTIVE_STAGING: Dict[int, str] = {}


class TornChainError(RuntimeError):
    """A chain checkpoint exists but one of its links (its base or an
    intermediate delta) is missing or unreadable, so the epoch cannot be
    reconstructed.  Resume paths catch this and fall back to the previous
    committed chain."""


def flatten_state(state: Any) -> Dict[str, Any]:
    """Flatten a pytree into ``{keystr(path): np.ndarray}`` — the on-host
    snapshot form the delta writer diffs and stores.  ``np.asarray`` on a
    ``jax.Array`` is the device→host copy; everything downstream of it is
    host-side work.  Key strings come from ``jax.tree_util.keystr`` and are
    stable for the dict/list/tuple trees training states are made of."""
    import numpy as np
    from jax.tree_util import keystr, tree_flatten_with_path
    flat = {}
    for path, leaf in tree_flatten_with_path(state)[0]:
        flat[keystr(path)] = np.asarray(leaf)
    return flat


def unflatten_like(like: Any, flat: Dict[str, Any]) -> Any:
    """Rebuild a pytree with ``like``'s structure from a flat snapshot.
    The key sets must match exactly — a template drift (renamed or added
    leaves) is a structural error, not something to paper over."""
    from jax.tree_util import keystr, tree_flatten_with_path, tree_unflatten
    paths_leaves, treedef = tree_flatten_with_path(like)
    keys = [keystr(p) for p, _ in paths_leaves]
    missing = [k for k in keys if k not in flat]
    extra = sorted(set(flat) - set(keys))
    if missing or extra:
        raise ValueError(
            f"chain checkpoint does not match the restore template: "
            f"missing leaves {missing[:4]!r}, unexpected leaves "
            f"{extra[:4]!r}")
    return tree_unflatten(treedef, [flat[k] for k in keys])


def _chain_manifest(directory: str, epoch: int) -> Optional[dict]:
    p = os.path.join(checkpoint_path(directory, epoch), CHAIN_MANIFEST)
    try:
        with open(p) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def is_chain(directory: str, epoch: int) -> bool:
    """True when ``checkpoint-{epoch}`` is a committed chain directory
    (vs a legacy orbax tree or nothing at all)."""
    return _chain_manifest(directory, epoch) is not None


def chain_links(directory: str, epoch: int) -> Optional[List[int]]:
    """Epochs to replay, base first, to reconstruct chain ``epoch`` —
    or None when the chain is torn (a link missing, unreadable, cyclic,
    or not anchored to a base)."""
    links: List[int] = []
    e = epoch
    while True:
        m = _chain_manifest(directory, e)
        if m is None:
            return None
        links.append(e)
        if m.get("kind") == "base":
            return list(reversed(links))
        prev = m.get("prev", -1)
        # prev must strictly decrease — anything else is corrupt/cyclic.
        if not isinstance(prev, int) or not 0 <= prev < e:
            return None
        e = prev


def _link_crc_ok(directory: str, epoch: int) -> bool:
    """Verify one chain link's shard file against the CRC32C its manifest
    recorded at commit.  Links from before the integrity trailer (no
    ``crc32c`` key) pass — there is nothing to check them against."""
    m = _chain_manifest(directory, epoch)
    want = None if m is None else m.get("crc32c")
    if want is None:
        return True
    from horovod_tpu import metrics, wire
    try:
        with open(os.path.join(checkpoint_path(directory, epoch),
                               CHAIN_SHARDS), "rb") as f:
            got = wire.crc32c(f.read())
    except OSError:
        return False
    if got != (want & 0xFFFFFFFF):
        metrics.registry.inc("ckpt.corrupt_links")
        return False
    return True


def _is_committed(directory: str, epoch: int) -> bool:
    """True when ``checkpoint-{epoch}`` is restorable: a legacy orbax dir
    (atomic-replace committed, hence complete) or a chain dir whose links
    are all intact AND whose shard bytes still match the CRC32C recorded
    at commit (a corrupt link makes the whole chain torn — the resume
    pivots to the prior committed chain, never loads flipped bits)."""
    if not os.path.isdir(checkpoint_path(directory, epoch)):
        return False
    if is_chain(directory, epoch):
        links = chain_links(directory, epoch)
        if links is None:
            return False
        return all(_link_crc_ok(directory, e) for e in links)
    return True


def save_chain(directory: str, flat: Dict[str, Any], epoch: int, *,
               prev_epoch: int = -1,
               prev_flat: Optional[Dict[str, Any]] = None,
               fault_hook=None) -> Dict[str, Any]:
    """Commit one chain epoch atomically: a full ``base`` when
    ``prev_flat`` is None (or the leaf set changed), else a ``delta``
    holding only the leaves whose bytes differ from ``prev_flat`` (the
    last COMMITTED snapshot, anchored at ``prev_epoch``).

    Same commit discipline as :func:`save`: world sidecar first, shards
    staged under a dot-prefixed dir ``latest_epoch`` can never match, one
    ``os.replace`` to publish.  ``fault_hook`` (chaos drills) runs after
    the shards are staged but before the commit — the worst place to die.

    Returns ``{"kind", "epoch", "nbytes", "shards", "total"}``.  The
    single-writer convention is the caller's job (ckpt_stream runs this
    on the owning rank's writer thread only).
    """
    import numpy as np
    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    path = checkpoint_path(directory, epoch)
    if prev_flat is not None and set(prev_flat) != set(flat):
        prev_flat = None   # leaf set changed: a delta cannot express it
    if prev_flat is None:
        changed = sorted(flat)
        kind = "base"
    else:
        changed = sorted(
            k for k, v in flat.items()
            if v.shape != prev_flat[k].shape
            or v.dtype != prev_flat[k].dtype
            or v.tobytes() != prev_flat[k].tobytes())
        kind = "delta"
    staging = os.path.join(directory,
                           f".tmp-checkpoint-{epoch}-{os.getpid()}")
    _ACTIVE_STAGING[epoch] = staging
    try:
        # Sidecar before the commit, same ordering argument as save().
        try:
            world = {"world_size": basics.size(),
                     "process_count": basics.process_count()}
        except Exception:
            world = None   # usable before init (tests, offline tools)
        if world is not None:
            _write_atomic(_world_meta_path(directory, epoch),
                          json.dumps(world))
        shutil.rmtree(staging, ignore_errors=True)
        os.makedirs(staging)
        np.savez(os.path.join(staging, CHAIN_SHARDS),
                 **{k: np.asarray(flat[k]) for k in changed})
        from horovod_tpu import wire
        with open(os.path.join(staging, CHAIN_SHARDS), "rb") as f:
            shard_crc = wire.crc32c(f.read())
        if fault_hook is not None:
            fault_hook()
        manifest = {"format": 1, "kind": kind, "epoch": epoch,
                    "prev": prev_epoch if kind == "delta" else -1,
                    "keys": sorted(flat), "shards": changed,
                    "crc32c": shard_crc}
        _write_atomic(os.path.join(staging, CHAIN_MANIFEST),
                      json.dumps(manifest))
        if os.path.isdir(path):
            shutil.rmtree(path)   # re-commit of the same epoch
        os.replace(staging, path)
    finally:
        _ACTIVE_STAGING.pop(epoch, None)
    nbytes = int(sum(np.asarray(flat[k]).nbytes for k in changed))
    return {"kind": kind, "epoch": epoch, "nbytes": nbytes,
            "shards": len(changed), "total": len(flat)}


def read_chain_state(directory: str, epoch: int) -> Dict[str, Any]:
    """Replay the base+delta chain ending at ``epoch`` into a flat
    snapshot.  Raises :class:`TornChainError` when the chain is torn."""
    import numpy as np
    links = chain_links(directory, epoch)
    if links is None:
        raise TornChainError(
            f"checkpoint-{epoch} in {directory!r} is a torn chain (a "
            f"base or delta link is missing); latest committed epoch "
            f"is {latest_epoch(directory)}")
    flat: Dict[str, Any] = {}
    for e in links:
        shard_path = os.path.join(checkpoint_path(directory, e),
                                  CHAIN_SHARDS)
        # End-to-end integrity: the manifest carries a CRC32C of the
        # shard file taken at commit; a mismatch (bit rot, a torn write
        # the rename discipline couldn't see, a chaos drill) makes the
        # whole chain torn — the caller falls back to the prior
        # committed chain instead of loading silently wrong numbers.
        if not _link_crc_ok(directory, e):
            raise TornChainError(
                f"checkpoint-{e} (link of chain {epoch}) in "
                f"{directory!r} is corrupt: shard CRC32C does not match "
                f"the manifest recorded at commit")
        try:
            with np.load(shard_path, allow_pickle=False) as z:
                for k in z.files:
                    flat[k] = z[k]
        except (OSError, ValueError) as exc:
            raise TornChainError(
                f"checkpoint-{e} (link of chain {epoch}) in "
                f"{directory!r} is unreadable: {exc}") from exc
    keys = _chain_manifest(directory, epoch)["keys"]
    missing = [k for k in keys if k not in flat]
    if missing:
        raise TornChainError(
            f"chain {epoch} in {directory!r} replayed without leaves "
            f"{missing[:4]!r} — base was overwritten by a narrower state")
    return {k: flat[k] for k in keys}


def resolve_committed_epoch(directory: str, epoch: int) -> int:
    """``epoch`` if it is committed (legacy or intact chain), else the
    highest committed epoch below it, else -1.  The torn-tip fallback:
    rank 0 runs this before the restore broadcast so no rank ever starts
    restoring an epoch that cannot be read."""
    if epoch >= 0 and _is_committed(directory, epoch):
        return epoch
    best = -1
    if os.path.isdir(directory):
        for entry in os.listdir(directory):
            m = re.fullmatch(r"checkpoint-(\d+)", entry)
            if m and best < int(m.group(1)) < epoch and _is_committed(
                    directory, int(m.group(1))):
                best = int(m.group(1))
    return best


def save(directory: str, state: Any, epoch: int) -> Optional[str]:
    """Write a checkpoint on rank 0 only; other ranks no-op (convention 1).

    ``state`` is any pytree (e.g. ``{"params": ..., "opt_state": ...}``).

    The commit is atomic: orbax writes into a dot-prefixed staging
    directory that :func:`latest_epoch` can never match, and a single
    ``os.replace`` publishes it as ``checkpoint-{epoch}``.  A crash
    mid-save therefore leaves debris (cleaned up by the next save), never
    a half-written directory a resume would restore from.
    """
    if basics.rank() != 0:
        return None
    directory = os.path.abspath(directory)
    path = checkpoint_path(directory, epoch)
    os.makedirs(directory, exist_ok=True)
    _clean_stale(directory)
    # World-size sidecar lands BEFORE the checkpoint commits (same
    # ordering argument as the optimizer spec): an elastic resume that
    # sees checkpoint-N can always tell what world wrote it.  An orphan
    # sidecar from a crash before the commit below is harmless —
    # latest_epoch only matches committed checkpoint dirs — and is
    # removed by the next save's _clean_stale.
    _write_atomic(_world_meta_path(directory, epoch),
                  json.dumps({"world_size": basics.size(),
                              "process_count": basics.process_count()}))
    staging = os.path.join(directory, f".tmp-checkpoint-{epoch}-{os.getpid()}")
    _checkpointer().save(staging, state, force=True)
    if os.path.isdir(path):
        shutil.rmtree(path)   # force=True re-save of the same epoch
    os.replace(staging, path)
    return path


def _write_atomic(path: str, text: str) -> None:
    """Publish ``text`` at ``path`` via a same-directory temp file and
    ``os.replace``, so no reader ever sees a partially-written file."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)


def _clean_stale(directory: str) -> None:
    """Remove debris a mid-save crash can leave behind: uncommitted
    staging directories, half-written sidecar temp files, and orphan
    sidecars whose checkpoint never committed.  Runs in the single
    writer (rank 0) at save time.  Staging dirs registered by a live
    async writer (``_ACTIVE_STAGING``) are in flight, not stale — the
    background delta writer may be mid-commit while a synchronous
    ``save()`` runs on the training thread."""
    entries = set(os.listdir(directory))
    active = {os.path.basename(p) for p in _ACTIVE_STAGING.values()}
    active_epochs = {f"checkpoint-{e}" for e in _ACTIVE_STAGING}
    for entry in entries:
        p = os.path.join(directory, entry)
        if re.fullmatch(r"\.tmp-checkpoint-\d+-\d+", entry):
            if entry not in active:
                shutil.rmtree(p, ignore_errors=True)
        elif re.fullmatch(
                r"checkpoint-\d+\.(world|optimizer)\.json\.tmp", entry):
            try:
                os.remove(p)
            except OSError:
                pass
        else:
            m = re.fullmatch(r"(checkpoint-\d+)\.(world|optimizer)\.json",
                             entry)
            if (m and m.group(1) not in entries
                    and m.group(1) not in active_epochs):
                try:
                    os.remove(p)
                except OSError:
                    pass


def _world_meta_path(directory: str, epoch: int) -> str:
    return checkpoint_path(directory, epoch) + ".world.json"


def saved_world_size(directory: str, epoch: int) -> int:
    """World size recorded when checkpoint ``epoch`` was written, or -1
    for checkpoints predating the sidecar (or an unreadable one)."""
    p = _world_meta_path(directory, epoch)
    try:
        with open(p) as f:
            return int(json.load(f).get("world_size", -1))
    except (OSError, ValueError):
        return -1


def _sharded_leaf_path(tree) -> Optional[str]:
    """Path of the first leaf laid out across devices (not fully
    replicated), or None.  Such state is bound to a specific world shape
    and cannot survive an elastic world-size change."""
    import jax
    from jax.tree_util import keystr, tree_flatten_with_path
    for path, leaf in tree_flatten_with_path(tree)[0]:
        if isinstance(leaf, jax.Array) and not leaf.is_fully_replicated:
            return keystr(path)
    return None


def latest_epoch(directory: str) -> int:
    """Highest epoch with a COMMITTED checkpoint in ``directory``, or -1.

    Mirrors the reference's resume-epoch scan
    (``examples/keras_imagenet_resnet50.py:64-70``: try epochs descending,
    first existing file wins).  Only committed checkpoint directories
    count: :func:`save` stages under a dot-prefixed name the pattern
    can never match and publishes atomically, so an entry seen here is
    complete — sidecars, stray files, and dot-prefixed staging debris
    from a crashed save are skipped.  A chain epoch additionally counts
    only when every link back to its base is intact, so a resume racing
    a crashed delta writer falls back past the torn tip.
    """
    if not os.path.isdir(directory):
        return -1
    best = -1
    for entry in os.listdir(directory):
        m = re.fullmatch(r"checkpoint-(\d+)", entry)
        if m and int(m.group(1)) > best and _is_committed(
                directory, int(m.group(1))):
            best = int(m.group(1))
    return best


def restore(directory: str, epoch: int, like: Any) -> Any:
    """Restore the checkpoint for ``epoch`` with the structure of ``like``.

    A chain epoch (async incremental stream) replays its base+delta links;
    raises :class:`TornChainError` if a link is missing.  A legacy orbax
    epoch restores with ``item=like`` so orbax rebuilds the original
    pytree structure (optax states are NamedTuples/tuples, which the
    stored metadata alone round-trips as lists).
    """
    import time
    t0 = time.perf_counter()
    if is_chain(directory, epoch):
        out = unflatten_like(like, read_chain_state(directory, epoch))
    else:
        import orbax.checkpoint as ocp
        path = checkpoint_path(directory, epoch)
        out = _checkpointer().restore(
            path, item=like,
            restore_args=ocp.checkpoint_utils.construct_restore_args(like))
    from horovod_tpu import metrics
    metrics.registry.observe("ckpt.restore_seconds",
                             time.perf_counter() - t0)
    return out


@dataclasses.dataclass
class OptimizerSpec:
    """Serializable optimizer identity — the optax analogue of the Keras
    optimizer config the reference persists inside its h5 files
    (``horovod/keras/__init__.py:113-148``: class name + hyperparams,
    reconstructed at load with ``custom_optimizers`` resolution).

    optax transforms are closures, so identity is declared rather than
    introspected: an ordered list of ``(factory, kwargs)`` steps, each
    factory a dotted import path (``"optax.adamw"``) or a name resolved
    from ``custom_objects`` at build time (the reference's
    ``custom_optimizers``/``custom_objects`` escape hatch).  Multiple
    steps rebuild as ``optax.chain(*steps)``.
    """

    steps: List[Tuple[str, Dict[str, Any]]]

    @classmethod
    def of(cls, factory: str, **kwargs) -> "OptimizerSpec":
        return cls([(factory, kwargs)])

    @classmethod
    def chain(cls, *steps) -> "OptimizerSpec":
        return cls([(f, dict(kw)) for f, kw in steps])

    def to_json(self) -> str:
        return json.dumps({"steps": [[f, kw] for f, kw in self.steps]})

    @classmethod
    def from_json(cls, text: str) -> "OptimizerSpec":
        data = json.loads(text)
        return cls([(f, kw) for f, kw in data["steps"]])

    def build(self, custom_objects: Optional[Dict[str, Any]] = None):
        import optax
        txs = []
        for factory, kwargs in self.steps:
            fn = None
            if custom_objects and factory in custom_objects:
                fn = custom_objects[factory]
            else:
                mod_name, _, attr = factory.rpartition(".")
                # The spec file sits on disk next to the checkpoint;
                # resolving arbitrary dotted paths from it would hand a
                # tampered directory code execution at resume.  Only the
                # optax namespace auto-imports — everything else must
                # come through the caller's custom_objects.
                if mod_name != "optax" and not mod_name.startswith(
                        "optax."):
                    raise ValueError(
                        f"optimizer factory {factory!r} is neither an "
                        f"optax.* path nor in custom_objects "
                        f"{sorted(custom_objects or {})}; pass it via "
                        "load_model(custom_objects={...})")
                fn = getattr(importlib.import_module(mod_name), attr)
            txs.append(fn(**kwargs))
        return txs[0] if len(txs) == 1 else optax.chain(*txs)


def _as_optimizer_spec(optimizer) -> OptimizerSpec:
    if isinstance(optimizer, OptimizerSpec):
        return optimizer
    if (isinstance(optimizer, tuple) and len(optimizer) == 2
            and isinstance(optimizer[0], str)):
        return OptimizerSpec([(optimizer[0], dict(optimizer[1]))])
    if isinstance(optimizer, list):
        return OptimizerSpec.chain(*optimizer)
    raise TypeError(
        "save_model(optimizer=...) takes an OptimizerSpec, a "
        "(factory, kwargs) tuple, or a list of them — a raw optax "
        "GradientTransformation is a closure and cannot be persisted; "
        "declare how to rebuild it instead (see checkpoint.OptimizerSpec)")


def _optimizer_spec_path(directory: str, epoch: int) -> str:
    return checkpoint_path(directory, epoch) + ".optimizer.json"


# ------------------------------------------------------ params skeleton
# load_model-with-only-a-directory needs every rank to hold a pytree of
# the right structure before the value broadcast; rank 0 derives this
# structural spec from the checkpoint's METADATA (shapes/dtypes only — no
# data read) and broadcasts it as bytes.  Orbax stores tuples as lists
# and JSON keys are strings, so a params tree containing tuple nodes or
# non-string dict keys cannot round-trip without an explicit
# ``params_like`` — :func:`save_model` warns at save time.

def _meta_to_spec(node) -> Any:
    if node is None:
        return {"t": "none"}
    if isinstance(node, dict):
        return {"t": "dict",
                "items": {k: _meta_to_spec(v) for k, v in node.items()}}
    if isinstance(node, (list, tuple)):
        return {"t": "list", "items": [_meta_to_spec(v) for v in node]}
    return {"t": "leaf", "dtype": str(node.dtype),
            "shape": list(node.shape)}


def _params_resume_safe(tree) -> bool:
    """True when the params tree survives the metadata→JSON→skeleton trip
    structurally intact: PLAIN dicts with string keys / plain lists, down
    to array-or-scalar leaves.  Anything else — tuples, FrozenDict-style
    mappings, custom pytree nodes — rebuilds as a different node type (or
    not at all) from the JSON skeleton, so it is reported unsafe and
    :func:`save_model` warns."""
    import numpy as np
    if type(tree) is dict:
        return (all(isinstance(k, str) for k in tree)
                and all(_params_resume_safe(v) for v in tree.values()))
    if type(tree) is list:
        return all(_params_resume_safe(v) for v in tree)
    if isinstance(tree, (np.ndarray, np.generic, int, float, complex)):
        return True
    import jax
    return isinstance(tree, jax.Array)


def _spec_to_skeleton(spec) -> Any:
    import jax.numpy as jnp
    t = spec["t"]
    if t == "none":
        return None
    if t == "dict":
        return {k: _spec_to_skeleton(v) for k, v in spec["items"].items()}
    if t == "list":
        return [_spec_to_skeleton(v) for v in spec["items"]]
    return jnp.zeros(tuple(spec["shape"]), jnp.dtype(spec["dtype"]))


def _broadcast_text(text: Optional[str], root_rank: int, name: str) -> str:
    """Broadcast a variable-length UTF-8 string from ``root_rank``:
    length first (fixed-shape negotiated broadcast), then the payload."""
    import numpy as np
    from horovod_tpu.ops import eager
    data = (text or "").encode("utf-8")
    n = int(np.asarray(eager.broadcast(
        np.asarray(len(data), np.int64), root_rank, name=f"{name}.len")))
    buf = np.zeros(n, np.uint8)
    if basics.rank() == root_rank:
        buf = np.frombuffer(data, np.uint8).copy()
    out = np.asarray(eager.broadcast(buf, root_rank, name=f"{name}.bytes"))
    return out.tobytes().decode("utf-8")


def save_model(directory: str, params: Any, opt_state: Any,
               epoch: int, optimizer=None) -> Optional[str]:
    """Save a full training state (params + optimizer state) under the
    ``{"params", "opt_state"}`` convention :func:`load_model` restores.
    Rank-0-only like :func:`save`.

    ``optimizer`` (an :class:`OptimizerSpec`, ``(factory, kwargs)`` tuple,
    or list of them) additionally persists the optimizer *identity* next
    to the checkpoint, enabling :func:`load_model` to resume from the
    directory alone — the reference's serialize-the-optimizer-too
    behaviour (``horovod/keras/__init__.py:113-148``)."""
    spec = _as_optimizer_spec(optimizer) if optimizer is not None else None
    if spec is not None and not _params_resume_safe(params):
        import warnings
        warnings.warn(
            "save_model: this params tree contains tuple nodes or "
            "non-string dict keys, which the directory-only load_model "
            "skeleton cannot reproduce (orbax stores tuples as lists; "
            "JSON keys are strings) — resuming will need an explicit "
            "params_like=.", stacklevel=2)
    # The spec lands BEFORE the checkpoint commits: a concurrent
    # directory-only load_model that sees checkpoint-N must always find
    # N's spec (a stale spec without its checkpoint is harmless —
    # latest_epoch only matches checkpoint dirs).
    if basics.rank() == 0 and spec is not None:
        os.makedirs(os.path.abspath(directory), exist_ok=True)
        _write_atomic(_optimizer_spec_path(directory, epoch),
                      spec.to_json())
    return save(directory, {"params": params, "opt_state": opt_state},
                epoch)


def load_model(directory: str, optimizer=None, params_like: Any = None, *,
               root_rank: int = 0, average: bool = True,
               compression=None, custom_objects=None):
    """One-call resume with the optimizer re-wrapped distributed — the
    reference's ``hvd.load_model`` (``horovod/keras/__init__.py:115-148``,
    ``_impl.py:93-109``: restore the saved model, reconstruct its
    optimizer from the file, wrap in DistributedOptimizer, broadcast).

    Args:
      directory: checkpoint directory written by :func:`save_model`.
      optimizer: the PLAIN optax optimizer (any chain, custom or not) —
        wrapped in :func:`horovod_tpu.jax.DistributedOptimizer` here,
        exactly like the reference rewraps the deserialized optimizer
        class.  **Omit it** to rebuild the optimizer from the
        :class:`OptimizerSpec` persisted by
        ``save_model(..., optimizer=...)``; ``custom_objects`` resolves
        non-importable factory names then (the reference's
        ``custom_optimizers``/``custom_objects``).
      params_like: a params pytree of the right structure/shapes (e.g.
        from ``model.init``) used both as the restore skeleton and as
        the fresh state when no checkpoint exists.  **Omit it** to derive
        the skeleton from the checkpoint's metadata (no data read; the
        structure is broadcast from rank 0).  Params built of
        string-keyed dicts / lists of arrays round-trip; tuple nodes,
        non-string keys, and custom pytree nodes need an explicit
        ``params_like`` (``save_model`` warns about such trees).
      average / compression: forwarded to ``DistributedOptimizer``.

    Returns ``(params, distributed_tx, opt_state, resume_epoch)``;
    ``resume_epoch`` is -1 (fresh params/opt_state, still broadcast from
    ``root_rank``) when the directory holds no checkpoint — starting
    fresh requires ``optimizer`` and ``params_like``.  The returned
    ``opt_state`` preserves the optimizer's own pytree structure through
    the round trip, custom chains included (the reference round-trips
    custom optimizers in ``test/test_keras.py:60-183``).
    """
    import numpy as np
    from horovod_tpu.compression import NoneCompressor
    from horovod_tpu.jax import DistributedOptimizer
    from horovod_tpu.ops import eager

    if compression is None:
        compression = NoneCompressor
    if isinstance(optimizer, OptimizerSpec):
        # Accept the same spec save_model's optimizer= takes — build it
        # rather than surfacing an AttributeError from optimizer.init.
        optimizer = optimizer.build(custom_objects)
    agreed_epoch = None
    if optimizer is None or params_like is None:
        # Directory-only resume: agree on the epoch ONCE, then both the
        # reconstruction here and the restore below use it — a checkpoint
        # landing concurrently must not split the spec/skeleton and the
        # weights across two different epochs.
        epoch = latest_epoch(directory) if basics.rank() == root_rank else -1
        epoch = int(np.asarray(eager.broadcast(
            np.asarray(epoch, np.int64), root_rank,
            name="ckpt.spec_epoch")))
        agreed_epoch = epoch
        if epoch < 0:
            raise FileNotFoundError(
                f"load_model: no checkpoint in {directory!r} to "
                "reconstruct from; pass optimizer= and params_like= to "
                "start fresh")
        if optimizer is None:
            spec_text = None
            if basics.rank() == root_rank:
                p = _optimizer_spec_path(directory, epoch)
                spec_text = open(p).read() if os.path.exists(p) else ""
            spec_text = _broadcast_text(spec_text, root_rank,
                                        "ckpt.optspec")
            if not spec_text:
                raise FileNotFoundError(
                    f"load_model: checkpoint-{epoch} in {directory!r} was "
                    "saved without an optimizer spec (save_model's "
                    "optimizer= argument); pass optimizer= explicitly")
            optimizer = OptimizerSpec.from_json(spec_text).build(
                custom_objects)
        if params_like is None:
            skel_json = None
            if basics.rank() == root_rank:
                # Metadata only — shapes/dtypes without reading the
                # checkpoint data (the values are read once, below, in
                # restore_and_broadcast).
                meta = _checkpointer().metadata(
                    checkpoint_path(directory, epoch))
                tree = meta.item_metadata.tree
                skel_json = json.dumps(_meta_to_spec(tree["params"]))
            skel_json = _broadcast_text(skel_json, root_rank, "ckpt.pskel")
            params_like = _spec_to_skeleton(json.loads(skel_json))
    tx = DistributedOptimizer(optimizer, average=average,
                              compression=compression)
    like = {"params": params_like, "opt_state": optimizer.init(params_like)}
    state, epoch = restore_and_broadcast(directory, like,
                                         root_rank=root_rank,
                                         epoch=agreed_epoch)
    return state["params"], tx, state["opt_state"], epoch


def restore_and_broadcast(directory: str, like: Any,
                          root_rank: int = 0,
                          epoch: Optional[int] = None,
                          optional_keys: Tuple[str, ...] = ()
                          ) -> Tuple[Any, int]:
    """Resume protocol (conventions 2+3): the resume epoch is agreed by
    broadcasting rank 0's scan; rank 0 restores; state is broadcast so all
    ranks start identical (reference ``keras_imagenet_resnet50.py:64-103``,
    ``pytorch_imagenet_resnet50.py:71,134-142``).

    Returns ``(state, resume_epoch)``; ``resume_epoch`` is -1 (and ``state``
    is ``like``, broadcast from root) when no checkpoint exists.  Pass an
    explicit ``epoch`` (already agreed across ranks) to restore that
    checkpoint instead of re-scanning — callers that derived other state
    from an epoch must restore the SAME one even if a new checkpoint
    lands concurrently.

    ``optional_keys`` (``like`` must be a dict): top-level template keys
    tolerated as absent on disk — rank 0 checks the checkpoint's
    metadata and the presence set is agreed across ranks BEFORE the
    value broadcast, so a checkpoint written by an older script version
    (e.g. without ``opt_state``) resumes cleanly — the corresponding
    ``like`` values pass through untouched — instead of rank 0 raising
    a tree-structure error while the other ranks hang in the broadcast.
    """
    import numpy as np
    from horovod_tpu.jax import broadcast_parameters
    from horovod_tpu.ops import eager

    if epoch is None:
        epoch = latest_epoch(directory) if basics.rank() == root_rank else -1
        epoch = int(np.asarray(eager.broadcast(
            np.asarray(epoch, np.int64), root_rank,
            name="ckpt.resume_epoch")))
    if epoch >= 0:
        # Torn-tip fallback, agreed BEFORE any value broadcast: rank 0
        # validates the chosen epoch is committed (an explicitly passed
        # epoch may be a chain whose base was lost, or debris from a
        # writer that died mid-commit) and every rank pivots to the same
        # fallback — rank 0 must never discover a torn chain after the
        # other ranks have entered the restore broadcast.
        tip = (resolve_committed_epoch(directory, epoch)
               if basics.rank() == root_rank else -1)
        tip = int(np.asarray(eager.broadcast(
            np.asarray(tip, np.int64), root_rank,
            name="ckpt.chain_tip")))
        if tip != epoch:
            print(
                f"horovod_tpu checkpoint: checkpoint-{epoch} in "
                f"{directory!r} is torn or missing; falling back to "
                + (f"committed checkpoint-{tip}" if tip >= 0
                   else "fresh state (no committed checkpoint)"),
                file=sys.stderr)
        epoch = tip
    if epoch >= 0:
        # Elastic resume: the world that wrote the checkpoint may be gone
        # (a rank was lost and the job reconfigured).  Replicated state
        # re-broadcasts from root at ANY world size; state laid out across
        # devices is bound to the old world shape and must fail with a
        # named leaf, not a shape error deep inside orbax.
        saved = (saved_world_size(directory, epoch)
                 if basics.rank() == root_rank else -1)
        saved = int(np.asarray(eager.broadcast(
            np.asarray(saved, np.int64), root_rank,
            name="ckpt.world_size")))
        cur = basics.size()
        if saved >= 0 and saved != cur:
            bad = _sharded_leaf_path(like)
            if bad is not None:
                raise ValueError(
                    f"restore_and_broadcast: checkpoint-{epoch} in "
                    f"{directory!r} was saved at world size {saved} but "
                    f"the job is now size {cur}, and template leaf "
                    f"{bad!r} is sharded across devices — sharded state "
                    "cannot be re-laid-out across a different world; "
                    "only replicated state survives an elastic "
                    "world-size change (see docs/elasticity.md)")
            print(
                f"horovod_tpu checkpoint: checkpoint-{epoch} was written "
                f"at world size {saved}; restoring into world size {cur} "
                f"— replicated state re-broadcast from rank {root_rank}",
                file=sys.stderr)
    if optional_keys and not isinstance(like, dict):
        # Fail on the FIRST call, not on the first resume after a
        # checkpoint exists.
        raise TypeError(
            "optional_keys needs a dict template (top-level keys)")
    if optional_keys and epoch >= 0:
        present = 0
        if basics.rank() == root_rank:
            if is_chain(directory, epoch):
                leaf_keys = _chain_manifest(directory, epoch)["keys"]
                present = sum(
                    1 << i for i, k in enumerate(optional_keys)
                    if any(s.startswith(f"['{k}']") for s in leaf_keys))
            else:
                tree = _checkpointer().metadata(
                    checkpoint_path(directory, epoch)).item_metadata.tree
                present = sum(1 << i for i, k in enumerate(optional_keys)
                              if k in tree)
        present = int(np.asarray(eager.broadcast(
            np.asarray(present, np.int64), root_rank,
            name="ckpt.optional_keys")))
        missing = {k for i, k in enumerate(optional_keys)
                   if not (present >> i) & 1}
        # Restore without the absent keys; their template values are
        # merged back before the broadcast below, so every rank ends
        # with root's copy of the defaults too (a fresh opt_state built
        # pre-broadcast may differ per rank).
        defaults = {k: like[k] for k in optional_keys
                    if k in missing and k in like}
        like = {k: v for k, v in like.items() if k not in missing}
    else:
        defaults = {}
    state = like
    if epoch >= 0 and basics.rank() == root_rank:
        state = restore(directory, epoch, like)
    if defaults:
        state = {**state, **defaults}
    state = broadcast_parameters(state, root_rank,
                                 name_prefix="ckpt.broadcast")
    return state, epoch
