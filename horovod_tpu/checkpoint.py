"""Checkpoint / resume utilities.

The reference has no core checkpoint subsystem; it establishes three
conventions the examples implement (SURVEY §5.4):

1. **rank-0-only writing** (``README.md`` step 6,
   ``examples/tensorflow_mnist_estimator.py:147``),
2. **resume = rank-0 restore + broadcast to all ranks** including the resume
   epoch (``examples/keras_imagenet_resnet50.py:64-103``), and
3. **optimizer-state rewrapping on load** (``hvd.load_model``,
   ``horovod/keras/__init__.py:115-148``; ``broadcast_optimizer_state`` for
   torch).

This module packages those conventions TPU-natively on orbax (the JAX
checkpointing library): save is a no-op off rank 0; restore happens on rank
0 and is broadcast through the framework's collective path so every rank
resumes bit-identical state.
"""

from __future__ import annotations

import os
import re
from typing import Any, Optional, Tuple

from horovod_tpu import basics


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.PyTreeCheckpointer()


def checkpoint_path(directory: str, epoch: int) -> str:
    # orbax requires absolute paths; accept relative ones at this API.
    return os.path.join(os.path.abspath(directory), f"checkpoint-{epoch}")


def save(directory: str, state: Any, epoch: int) -> Optional[str]:
    """Write a checkpoint on rank 0 only; other ranks no-op (convention 1).

    ``state`` is any pytree (e.g. ``{"params": ..., "opt_state": ...}``).
    """
    if basics.rank() != 0:
        return None
    path = checkpoint_path(directory, epoch)
    _checkpointer().save(path, state, force=True)
    return path


def latest_epoch(directory: str) -> int:
    """Highest epoch with a checkpoint in ``directory``, or -1.

    Mirrors the reference's resume-epoch scan
    (``examples/keras_imagenet_resnet50.py:64-70``: try epochs descending,
    first existing file wins).
    """
    if not os.path.isdir(directory):
        return -1
    best = -1
    for entry in os.listdir(directory):
        m = re.fullmatch(r"checkpoint-(\d+)", entry)
        if m:
            best = max(best, int(m.group(1)))
    return best


def restore(directory: str, epoch: int, like: Any) -> Any:
    """Restore the checkpoint for ``epoch`` with the structure of ``like``.

    Passing ``item=like`` makes orbax rebuild the original pytree structure
    (optax states are NamedTuples/tuples, which the stored metadata alone
    round-trips as lists).
    """
    import orbax.checkpoint as ocp
    path = checkpoint_path(directory, epoch)
    return _checkpointer().restore(
        path, item=like,
        restore_args=ocp.checkpoint_utils.construct_restore_args(like))


def restore_and_broadcast(directory: str, like: Any,
                          root_rank: int = 0) -> Tuple[Any, int]:
    """Resume protocol (conventions 2+3): the resume epoch is agreed by
    broadcasting rank 0's scan; rank 0 restores; state is broadcast so all
    ranks start identical (reference ``keras_imagenet_resnet50.py:64-103``,
    ``pytorch_imagenet_resnet50.py:71,134-142``).

    Returns ``(state, resume_epoch)``; ``resume_epoch`` is -1 (and ``state``
    is ``like``, broadcast from root) when no checkpoint exists.
    """
    import numpy as np
    from horovod_tpu.jax import broadcast_parameters
    from horovod_tpu.ops import eager

    epoch = latest_epoch(directory) if basics.rank() == root_rank else -1
    epoch = int(np.asarray(eager.broadcast(
        np.asarray(epoch, np.int64), root_rank, name="ckpt.resume_epoch")))
    state = like
    if epoch >= 0 and basics.rank() == root_rank:
        state = restore(directory, epoch, like)
    state = broadcast_parameters(state, root_rank,
                                 name_prefix="ckpt.broadcast")
    return state, epoch
