"""Checkpoint / resume utilities.

The reference has no core checkpoint subsystem; it establishes three
conventions the examples implement (SURVEY §5.4):

1. **rank-0-only writing** (``README.md`` step 6,
   ``examples/tensorflow_mnist_estimator.py:147``),
2. **resume = rank-0 restore + broadcast to all ranks** including the resume
   epoch (``examples/keras_imagenet_resnet50.py:64-103``), and
3. **optimizer-state rewrapping on load** (``hvd.load_model``,
   ``horovod/keras/__init__.py:115-148``; ``broadcast_optimizer_state`` for
   torch).

This module packages those conventions TPU-natively on orbax (the JAX
checkpointing library): save is a no-op off rank 0; restore happens on rank
0 and is broadcast through the framework's collective path so every rank
resumes bit-identical state.
"""

from __future__ import annotations

import os
import re
from typing import Any, Optional, Tuple

from horovod_tpu import basics


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.PyTreeCheckpointer()


def checkpoint_path(directory: str, epoch: int) -> str:
    # orbax requires absolute paths; accept relative ones at this API.
    return os.path.join(os.path.abspath(directory), f"checkpoint-{epoch}")


def save(directory: str, state: Any, epoch: int) -> Optional[str]:
    """Write a checkpoint on rank 0 only; other ranks no-op (convention 1).

    ``state`` is any pytree (e.g. ``{"params": ..., "opt_state": ...}``).
    """
    if basics.rank() != 0:
        return None
    path = checkpoint_path(directory, epoch)
    _checkpointer().save(path, state, force=True)
    return path


def latest_epoch(directory: str) -> int:
    """Highest epoch with a checkpoint in ``directory``, or -1.

    Mirrors the reference's resume-epoch scan
    (``examples/keras_imagenet_resnet50.py:64-70``: try epochs descending,
    first existing file wins).
    """
    if not os.path.isdir(directory):
        return -1
    best = -1
    for entry in os.listdir(directory):
        m = re.fullmatch(r"checkpoint-(\d+)", entry)
        if m:
            best = max(best, int(m.group(1)))
    return best


def restore(directory: str, epoch: int, like: Any) -> Any:
    """Restore the checkpoint for ``epoch`` with the structure of ``like``.

    Passing ``item=like`` makes orbax rebuild the original pytree structure
    (optax states are NamedTuples/tuples, which the stored metadata alone
    round-trips as lists).
    """
    import orbax.checkpoint as ocp
    path = checkpoint_path(directory, epoch)
    return _checkpointer().restore(
        path, item=like,
        restore_args=ocp.checkpoint_utils.construct_restore_args(like))


def save_model(directory: str, params: Any, opt_state: Any,
               epoch: int) -> Optional[str]:
    """Save a full training state (params + optimizer state) under the
    ``{"params", "opt_state"}`` convention :func:`load_model` restores.
    Rank-0-only like :func:`save`."""
    return save(directory, {"params": params, "opt_state": opt_state},
                epoch)


def load_model(directory: str, optimizer, params_like: Any, *,
               root_rank: int = 0, average: bool = True,
               compression=None):
    """One-call resume with the optimizer re-wrapped distributed — the
    reference's ``hvd.load_model`` (``horovod/keras/__init__.py:115-148``,
    ``_impl.py:93-109``: restore the saved model, wrap its optimizer in
    DistributedOptimizer, broadcast).

    Args:
      directory: checkpoint directory written by :func:`save_model`.
      optimizer: the PLAIN optax optimizer (any chain, custom or not) —
        it is wrapped in :func:`horovod_tpu.jax.DistributedOptimizer`
        here, exactly like the reference rewraps the deserialized
        optimizer class.
      params_like: a params pytree of the right structure/shapes (e.g.
        from ``model.init``) used both as the restore skeleton and as
        the fresh state when no checkpoint exists.
      average / compression: forwarded to ``DistributedOptimizer``.

    Returns ``(params, distributed_tx, opt_state, resume_epoch)``;
    ``resume_epoch`` is -1 (fresh params/opt_state, still broadcast from
    ``root_rank``) when the directory holds no checkpoint.  The returned
    ``opt_state`` preserves the optimizer's own pytree structure through
    the round trip, custom chains included (the reference round-trips
    custom optimizers in ``test/test_keras.py:60-183``).
    """
    from horovod_tpu.compression import NoneCompressor
    from horovod_tpu.jax import DistributedOptimizer

    if compression is None:
        compression = NoneCompressor
    tx = DistributedOptimizer(optimizer, average=average,
                              compression=compression)
    like = {"params": params_like, "opt_state": optimizer.init(params_like)}
    state, epoch = restore_and_broadcast(directory, like,
                                         root_rank=root_rank)
    return state["params"], tx, state["opt_state"], epoch


def restore_and_broadcast(directory: str, like: Any,
                          root_rank: int = 0) -> Tuple[Any, int]:
    """Resume protocol (conventions 2+3): the resume epoch is agreed by
    broadcasting rank 0's scan; rank 0 restores; state is broadcast so all
    ranks start identical (reference ``keras_imagenet_resnet50.py:64-103``,
    ``pytorch_imagenet_resnet50.py:71,134-142``).

    Returns ``(state, resume_epoch)``; ``resume_epoch`` is -1 (and ``state``
    is ``like``, broadcast from root) when no checkpoint exists.
    """
    import numpy as np
    from horovod_tpu.jax import broadcast_parameters
    from horovod_tpu.ops import eager

    epoch = latest_epoch(directory) if basics.rank() == root_rank else -1
    epoch = int(np.asarray(eager.broadcast(
        np.asarray(epoch, np.int64), root_rank, name="ckpt.resume_epoch")))
    state = like
    if epoch >= 0 and basics.rank() == root_rank:
        state = restore(directory, epoch, like)
    state = broadcast_parameters(state, root_rank,
                                 name_prefix="ckpt.broadcast")
    return state, epoch
