"""Background controller: negotiation, fusion planning, handle management.

This is the TPU-native re-design of the reference's C++ core
(``horovod/common/operations.cc``):

* A per-process **background thread** owns all control-plane state; framework
  threads only enqueue work and receive callbacks — the reference's key
  architectural invariant (``operations.cc:106-111, 1414-1433``).
* **Negotiation**: a message table counts per-tensor readiness across ranks;
  when every rank has submitted a tensor, a response is constructed with full
  cross-rank validation (mismatched dtype / op / shape / root-rank errors,
  message text matching ``ConstructMPIResponse``,
  ``operations.cc:315-517``).
* **Fusion planner**: consecutive same-dtype allreduce responses are merged
  while their payload stays under the fusion threshold
  (``operations.cc:1807-1842``; default 64 MB, ``operations.cc:151``).
* **Data plane**: instead of MPI/NCCL calls, ready responses are executed as
  jitted XLA programs over the device mesh (:mod:`horovod_tpu.ops.executor`).

The control-plane state machine also exists as a C++ library
(``cpp/``, loaded via ctypes in :mod:`horovod_tpu.cpp_core`); when the shared
library is available it replaces the pure-Python message table / fusion /
timeline / stall-check logic below.  Behaviour is identical; the Python path
is the fallback and the executable specification.
"""

from __future__ import annotations

import collections
import dataclasses
import enum
import os
import sys
import threading
import time
import traceback
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from horovod_tpu import metrics as _metrics


# --------------------------------------------------------------------------
# Status (mirrors horovod/common/common.h:37-53)
# --------------------------------------------------------------------------

class StatusType(enum.IntEnum):
    OK = 0
    UNKNOWN_ERROR = 1
    PRECONDITION_ERROR = 2
    ABORTED = 3
    INVALID_ARGUMENT = 4
    # Elastic membership changed while this collective was in flight: the
    # operation did NOT complete, but the job survives — restore from the
    # latest checkpoint and resubmit (HorovodRetryableError, not
    # HorovodAbortedError).
    RETRYABLE = 5


@dataclasses.dataclass(frozen=True)
class Status:
    type: StatusType = StatusType.OK
    reason: str = ""

    def ok(self) -> bool:
        return self.type == StatusType.OK

    @staticmethod
    def OK() -> "Status":
        return Status()

    @staticmethod
    def precondition_error(msg: str) -> "Status":
        return Status(StatusType.PRECONDITION_ERROR, msg)

    @staticmethod
    def aborted(msg: str) -> "Status":
        return Status(StatusType.ABORTED, msg)

    @staticmethod
    def retryable(msg: str) -> "Status":
        return Status(StatusType.RETRYABLE, msg)

    @staticmethod
    def invalid_argument(msg: str) -> "Status":
        return Status(StatusType.INVALID_ARGUMENT, msg)


def env_flag(name: str) -> bool:
    """0/1-convention env flag (the reference treats any set value as true
    but documents 0/1; '0'/'false'/'' stay false here to avoid surprises)."""
    return os.environ.get(name, "").strip().lower() not in ("", "0", "false")


SHUT_DOWN_ERROR = Status.aborted(
    "Horovod has been shut down. This has been caused by an exception on one "
    "of the ranks or an attempt to allreduce, allgather or broadcast a tensor "
    "after one of the ranks has finished execution.")
# (error text parity: reference operations.cc:258-263)


# --------------------------------------------------------------------------
# Fault injection (HOROVOD_TPU_FAULT) — test-only failure triggers
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """Parsed HOROVOD_TPU_FAULT=<mode>:rank=<R>:tick=<T> spec (or
    ``crash_in_save:rank=<R>:epoch=<E>``, the checkpoint-writer fault,
    or ``slow:rank=<R>:ms=<M>[:tick=<T>]``, the planted straggler).

    The native core parses the same env var itself (control.cc) and fires
    the tick-based faults on the tick thread; ``crash_in_save`` is
    Python-owned (ckpt_stream.py fires it mid-commit) and the native
    parser skips it.  ``slow`` fires in whichever controller runs the
    tick — the native plane in multi-process jobs, the local Python loop
    otherwise — delaying the target's tick by M ms from tick T onward
    (every tick when tick= is omitted).  This Python-side parse exists to
    reject malformed specs loudly at init() instead of silently never
    firing.
    """
    mode: str      # "crash" | "hang" | "drop_conn" | "rejoin"
                   # | "crash_in_save" | "slow" | "corrupt" | "corrupt_ckpt"
    rank: int      # first global rank of the target process
    tick: int      # 1-based negotiation tick on which the fault fires;
                   # for crash_in_save/corrupt_ckpt, the 0-based snapshot
                   # epoch; for slow, the first delayed tick (-1 = from
                   # the start)
    ms: int = 0    # slow only: per-tick delay in milliseconds
    leg: str = "classic"  # corrupt only: which data-plane leg to mangle
                          # ("classic" | "shm" | "uring" | "ctrl")
    count: int = 1        # corrupt only: how many frames/chunks to flip

    @property
    def epoch(self) -> int:
        """crash_in_save's trigger: first committed snapshot epoch >= this
        value kills the writer mid-commit.  For corrupt_ckpt, the epoch
        whose committed shard file gets its bytes flipped."""
        return self.tick


_FAULT_MODES = ("crash", "hang", "drop_conn", "rejoin", "crash_in_save",
                "slow", "corrupt", "corrupt_ckpt")

_CORRUPT_LEGS = ("classic", "shm", "uring", "ctrl")


def parse_fault_spec(spec: str) -> Optional[FaultSpec]:
    """Strictly parse ONE fault spec; None for empty, ValueError on
    malformed.  ``rejoin`` arms the coordinator to admit parked standby
    workers at the first tick >= T (elastic mode's deterministic readmit
    trigger); ``crash_in_save`` takes ``epoch=`` instead of ``tick=``
    (epochs are step numbers, counted from 0) and kills the async
    checkpoint writer between staging its shards and committing them;
    ``corrupt`` flips a payload byte post-checksum pre-send on the chosen
    data-plane leg; ``corrupt_ckpt`` flips bytes in a committed shard
    file (Python-owned, like crash_in_save — the native parser skips
    both)."""
    spec = (spec or "").strip()
    if not spec:
        return None
    parts = spec.split(":")
    if parts[0] == "slow":
        # slow:rank=<R>:ms=<M>[:tick=<T>] — a planted straggler: delay
        # the target process's tick by M milliseconds, from tick T
        # onward (every tick when tick= is omitted).
        if len(parts) not in (3, 4):
            raise ValueError(
                f"Malformed HOROVOD_TPU_FAULT {spec!r}: expected "
                "'slow:rank=<R>:ms=<M>[:tick=<T>]'.")
        kv = {}
        for part in parts[1:]:
            key, sep, val = part.partition("=")
            if not sep or key not in ("rank", "ms", "tick") or key in kv:
                raise ValueError(
                    f"Malformed HOROVOD_TPU_FAULT {spec!r}: expected "
                    "'slow:rank=<R>:ms=<M>[:tick=<T>]'.")
            try:
                kv[key] = int(val)
            except ValueError:
                raise ValueError(
                    f"Malformed HOROVOD_TPU_FAULT {spec!r}: {key!r} must "
                    f"be an integer, got {val!r}.") from None
        if "rank" not in kv or "ms" not in kv:
            raise ValueError(
                f"Malformed HOROVOD_TPU_FAULT {spec!r}: both rank= and "
                "ms= are required.")
        if kv["rank"] < 0:
            raise ValueError(
                f"Malformed HOROVOD_TPU_FAULT {spec!r}: rank must be >= 0.")
        if kv["ms"] <= 0:
            raise ValueError(
                f"Malformed HOROVOD_TPU_FAULT {spec!r}: ms must be >= 1.")
        if "tick" in kv and kv["tick"] <= 0:
            raise ValueError(
                f"Malformed HOROVOD_TPU_FAULT {spec!r}: tick must be >= 1 "
                "(ticks are counted from 1).")
        return FaultSpec("slow", kv["rank"], kv.get("tick", -1), kv["ms"])
    if parts[0] == "corrupt":
        # corrupt:rank=<R>:tick=<T>[:leg=<L>][:count=<N>] — flip a byte in
        # a data-plane payload post-checksum, pre-send, on the chosen leg
        # (classic socket ring by default), starting at tick T, N times.
        if len(parts) not in (3, 4, 5):
            raise ValueError(
                f"Malformed HOROVOD_TPU_FAULT {spec!r}: expected "
                "'corrupt:rank=<R>:tick=<T>[:leg=<L>][:count=<N>]'.")
        kv = {}
        for part in parts[1:]:
            key, sep, val = part.partition("=")
            if not sep or key not in ("rank", "tick", "leg", "count") \
                    or key in kv:
                raise ValueError(
                    f"Malformed HOROVOD_TPU_FAULT {spec!r}: expected "
                    "'corrupt:rank=<R>:tick=<T>[:leg=<L>][:count=<N>]'.")
            if key == "leg":
                kv[key] = val
                continue
            try:
                kv[key] = int(val)
            except ValueError:
                raise ValueError(
                    f"Malformed HOROVOD_TPU_FAULT {spec!r}: {key!r} must "
                    f"be an integer, got {val!r}.") from None
        if "rank" not in kv or "tick" not in kv:
            raise ValueError(
                f"Malformed HOROVOD_TPU_FAULT {spec!r}: both rank= and "
                "tick= are required.")
        if kv["rank"] < 0:
            raise ValueError(
                f"Malformed HOROVOD_TPU_FAULT {spec!r}: rank must be >= 0.")
        if kv["tick"] <= 0:
            raise ValueError(
                f"Malformed HOROVOD_TPU_FAULT {spec!r}: tick must be >= 1 "
                "(ticks are counted from 1).")
        leg = kv.get("leg", "classic")
        if leg not in _CORRUPT_LEGS:
            raise ValueError(
                f"Malformed HOROVOD_TPU_FAULT {spec!r}: leg must be one of "
                f"{'|'.join(_CORRUPT_LEGS)}, got {leg!r}.")
        if kv.get("count", 1) <= 0:
            raise ValueError(
                f"Malformed HOROVOD_TPU_FAULT {spec!r}: count must be >= 1.")
        return FaultSpec("corrupt", kv["rank"], kv["tick"], 0, leg,
                         kv.get("count", 1))
    if len(parts) != 3 or parts[0] not in _FAULT_MODES:
        raise ValueError(
            f"Malformed HOROVOD_TPU_FAULT {spec!r}: expected "
            "'<crash|hang|drop_conn|rejoin>:rank=<R>:tick=<T>', "
            "'crash_in_save:rank=<R>:epoch=<E>', "
            "'corrupt_ckpt:rank=<R>:epoch=<E>', "
            "'corrupt:rank=<R>:tick=<T>[:leg=<L>][:count=<N>]' or "
            "'slow:rank=<R>:ms=<M>[:tick=<T>]'.")
    when_key = ("epoch" if parts[0] in ("crash_in_save", "corrupt_ckpt")
                else "tick")
    kv = {}
    for part in parts[1:]:
        key, sep, val = part.partition("=")
        if not sep or key not in ("rank", when_key) or key in kv:
            raise ValueError(
                f"Malformed HOROVOD_TPU_FAULT {spec!r}: expected "
                f"'{parts[0]}:rank=<R>:{when_key}=<N>'.")
        try:
            kv[key] = int(val)
        except ValueError:
            raise ValueError(
                f"Malformed HOROVOD_TPU_FAULT {spec!r}: {key!r} must be an "
                f"integer, got {val!r}.") from None
    if "rank" not in kv or when_key not in kv:
        raise ValueError(
            f"Malformed HOROVOD_TPU_FAULT {spec!r}: both rank= and "
            f"{when_key}= are required.")
    if kv["rank"] < 0:
        raise ValueError(
            f"Malformed HOROVOD_TPU_FAULT {spec!r}: rank must be >= 0.")
    if when_key == "tick" and kv["tick"] <= 0:
        raise ValueError(
            f"Malformed HOROVOD_TPU_FAULT {spec!r}: tick must be >= 1 "
            "(ticks are counted from 1).")
    if when_key == "epoch" and kv["epoch"] < 0:
        raise ValueError(
            f"Malformed HOROVOD_TPU_FAULT {spec!r}: epoch must be >= 0.")
    return FaultSpec(parts[0], kv["rank"], kv[when_key])


def parse_fault_specs(value: str) -> List[FaultSpec]:
    """Parse a full HOROVOD_TPU_FAULT value: one spec, or several separated
    by ';' (elastic scenarios script a kill and a later readmit together,
    e.g. ``crash:rank=1:tick=30;rejoin:rank=0:tick=60``)."""
    out: List[FaultSpec] = []
    for piece in (value or "").split(";"):
        parsed = parse_fault_spec(piece)
        if parsed is not None:
            out.append(parsed)
    return out


# --------------------------------------------------------------------------
# Wire message equivalents (reference horovod/common/mpi_message.{h,cc})
# --------------------------------------------------------------------------

class RequestType(enum.IntEnum):
    ALLREDUCE = 0
    ALLGATHER = 1
    BROADCAST = 2


class ResponseType(enum.IntEnum):
    ALLREDUCE = 0
    ALLGATHER = 1
    BROADCAST = 2
    ERROR = 3


_REQUEST_TYPE_NAME = {
    RequestType.ALLREDUCE: "ALLREDUCE",
    RequestType.ALLGATHER: "ALLGATHER",
    RequestType.BROADCAST: "BROADCAST",
}


def request_type_name(t: RequestType) -> str:
    return _REQUEST_TYPE_NAME.get(t, "<unknown>")


def dtype_name(dtype) -> str:
    """numpy-style dtype names match the reference's MPIDataType_Name
    (``mpi_message.cc:24-60``): uint8, int8, ..., float32, float64, bool."""
    return np.dtype(dtype).name


def shape_debug_string(shape: Sequence[int]) -> str:
    """Format parity with ``TensorShape::DebugString`` (common.cc)."""
    return "[" + ", ".join(str(d) for d in shape) + "]"


def normalize_wire_dtype(wire_dtype: str) -> str:
    """Canonicalize a wire-compression name; raises on unknown names.

    Delegates to the shared canonicalizer in
    :mod:`horovod_tpu.compression` so the eager ring and the in-jit
    plane accept the same names with the same rejection message."""
    from horovod_tpu.compression import canonical_wire_dtype
    return canonical_wire_dtype(wire_dtype, source="wire dtype")


def default_wire_dtype() -> str:
    """Process-wide ring compression default from HOROVOD_TPU_WIRE_DTYPE
    ("" when unset → raw fp32 wire)."""
    from horovod_tpu.compression import canonical_wire_dtype
    return canonical_wire_dtype(
        os.environ.get("HOROVOD_TPU_WIRE_DTYPE", ""),
        source="HOROVOD_TPU_WIRE_DTYPE")


# Canonical allreduce algorithm names.  "" = flat ring (the canonical form
# of "ring"); "hier" = two-level hierarchical; "small" = latency-optimal
# small-tensor path; "auto" = coordinator picks per payload (request-side
# only — responses always carry a resolved concrete algorithm).  Mirrors
# ResolveAlgo in cpp/htpu/message_table.cc.
_ALGO_ALIASES = {
    "": "", "ring": "", "flat": "",
    "hier": "hier", "hierarchical": "hier",
    "small": "small", "latency": "small",
    "auto": "auto",
}

# Payload size at/below which "auto" picks the small-tensor path
# (kDefaultAlgoCrossoverBytes, cpp/htpu/message_table.h); override with
# HOROVOD_TPU_ALLREDUCE_CROSSOVER, measure with `bench.py --tcp-allreduce`.
DEFAULT_ALGO_CROSSOVER_BYTES = 64 * 1024


def normalize_allreduce_algo(algo: str) -> str:
    """Canonicalize an allreduce algorithm name; raises on unknown names."""
    key = (algo or "").strip().lower()
    if key not in _ALGO_ALIASES:
        raise ValueError(
            f"Unknown allreduce algorithm {algo!r}: expected one of "
            "ring, hier, small, auto.")
    return _ALGO_ALIASES[key]


def default_allreduce_algo() -> str:
    """Process-wide allreduce algorithm preference from
    HOROVOD_TPU_ALLREDUCE_ALGO ("auto" when unset/empty)."""
    raw = os.environ.get("HOROVOD_TPU_ALLREDUCE_ALGO", "").strip()
    return "auto" if not raw else normalize_allreduce_algo(raw)


def algo_crossover_bytes() -> int:
    """Small-path crossover from HOROVOD_TPU_ALLREDUCE_CROSSOVER (bytes);
    malformed/negative values fall back to the default — same leniency as
    the native parser in control.cc."""
    raw = os.environ.get("HOROVOD_TPU_ALLREDUCE_CROSSOVER", "")
    try:
        v = int(raw)
        return v if v >= 0 else DEFAULT_ALGO_CROSSOVER_BYTES
    except ValueError:
        return DEFAULT_ALGO_CROSSOVER_BYTES


@dataclasses.dataclass
class Request:
    """One rank's announcement that a named tensor is ready
    (reference ``MPIRequest``, ``mpi_message.h``)."""
    request_rank: int
    request_type: RequestType
    tensor_name: str
    tensor_type: str                       # numpy dtype name
    tensor_shape: Tuple[int, ...]
    root_rank: int = -1
    device: int = -1                       # global device rank (or -1 host)
    # Requested ring wire compression ("" = raw fp32; "bf16"/"fp16"/"int8"
    # — cpp/htpu/quantize.h).  Validated across ranks like tensor_type.
    wire_dtype: str = ""
    # Requested allreduce algorithm preference ("" = ring, "hier", "small",
    # or "auto" for coordinator selection).  Validated across ranks like
    # wire_dtype; resolved to a concrete algorithm in the response.
    algo: str = ""
    # Process set this request negotiates in (0 = the default/world set).
    # Non-default sets carry SET-LOCAL request_rank (device stays the
    # global rank) and route to that set's message table.  Serialized only
    # when the enclosing list sets FLAG_SET_EXT.
    process_set: int = 0


@dataclasses.dataclass
class Response:
    """Coordinator's instruction to execute (possibly fused) collectives
    (reference ``MPIResponse``)."""
    response_type: ResponseType
    tensor_names: List[str]
    error_message: str = ""
    devices: List[int] = dataclasses.field(default_factory=list)
    # For allgather: dim0 size contributed by each rank, indexed by rank
    # (reference mpi_message.h tensor_sizes).
    tensor_sizes: List[int] = dataclasses.field(default_factory=list)
    # Negotiated wire compression (uniform across ranks by validation);
    # fusion only merges responses with equal wire dtypes.
    wire_dtype: str = ""
    # Resolved allreduce algorithm ("" = ring, "hier", "small" — never
    # "auto"); fusion only merges responses with equal algorithms, and the
    # response cache replays the resolution byte-exactly.
    algo: str = ""
    # Process set this response belongs to (0 = default/world).  Receivers
    # only pop entries whose process_set matches, so two tenants reusing a
    # tensor name never cross-execute.  Serialized only under FLAG_SET_EXT.
    process_set: int = 0


# --------------------------------------------------------------------------
# Message table: negotiation + cross-rank validation
# --------------------------------------------------------------------------

class MessageTable:
    """Tracks per-tensor readiness across ranks (coordinator side).

    Mirrors ``IncrementTensorCount`` / ``ConstructMPIResponse``
    (``operations.cc:282-517``) including error-message text.
    """

    def __init__(self, size: int, timeline=None):
        self._size = size
        self._table: Dict[str, Tuple[List[Request], float]] = {}
        self._timeline = timeline
        # Allreduce algorithm-selection inputs (configure_algo_selection);
        # defaults describe a single-host, single-process job, under which
        # "auto" resolves to small/ring only.
        self._algo_num_hosts = 1
        self._algo_num_procs = 1
        self._algo_crossover = DEFAULT_ALGO_CROSSOVER_BYTES

    def __len__(self):
        return len(self._table)

    def configure_algo_selection(self, num_hosts: int, num_procs: int,
                                 crossover_bytes: int) -> None:
        """Topology + crossover inputs for allreduce algorithm resolution
        (mirrors MessageTable::ConfigureAlgoSelection, message_table.cc)."""
        self._algo_num_hosts = max(1, num_hosts)
        self._algo_num_procs = max(1, num_procs)
        self._algo_crossover = max(0, crossover_bytes)

    def _resolve_algo(self, pref: str, nbytes: int) -> str:
        """Concrete algorithm for one allreduce (ResolveAlgo parity):
        explicit preferences pass through; "auto" picks the small path at or
        below the crossover, the hierarchical path when the job spans
        multiple hosts with co-located processes, else the flat ring."""
        from . import scheduler as _scheduler
        return _scheduler.resolve_algo(
            pref, nbytes, self._algo_num_hosts, self._algo_num_procs,
            self._algo_crossover)

    def clear(self):
        self._table.clear()

    def increment(self, msg: Request) -> bool:
        """Record one rank's request; True when all ranks have reported."""
        name = msg.tensor_name
        entry = self._table.get(name)
        if entry is None:
            self._table[name] = ([msg], time.monotonic())
            if self._timeline:
                self._timeline.negotiate_start(name, msg.request_type)
        else:
            entry[0].append(msg)
        if self._timeline:
            self._timeline.negotiate_rank_ready(name, msg.request_rank)
        ready = len(self._table[name][0]) == self._size
        if ready and self._timeline:
            self._timeline.negotiate_end(name)
        return ready

    def pending_names_older_than(
            self, age_s: float) -> List[Tuple[str, float, List[int]]]:
        """(name, age_s, missing_ranks) for entries older than ``age_s`` —
        the stall detector's input (``CheckForStalledTensors``,
        ``operations.cc:1366-1412``).  Same record shape as the native
        table's stall report (cpp/htpu/message_table.h StallInfo)."""
        now = time.monotonic()
        out = []
        for name, (reqs, t0) in self._table.items():
            if now - t0 > age_s:
                have = {r.request_rank for r in reqs}
                missing = [r for r in range(self._size) if r not in have]
                out.append((name, now - t0, missing))
        return out

    def construct_response(self, name: str) -> Response:
        """Validate all ranks' requests for ``name`` and build the response.

        Validation order and error text mirror ``ConstructMPIResponse``
        (``operations.cc:315-517``): dtype, op, shape (allreduce/broadcast),
        allgather rank/ dims, broadcast root rank.
        """
        requests, _ = self._table[name]
        assert requests
        error = None

        data_type = requests[0].tensor_type
        for r in requests[1:]:
            if r.tensor_type != data_type:
                error = (f"Mismatched data types: One rank had type {data_type}, "
                         f"but another rank had type {r.tensor_type}.")
                break

        # Wire compression must be uniform too: the ring's hops re-encode
        # with the negotiated wire dtype, so disagreeing ranks would desync
        # the byte stream.  Same coordinated-error style as the dtype check.
        if error is None:
            wire0 = requests[0].wire_dtype
            for r in requests[1:]:
                if r.wire_dtype != wire0:
                    error = ("Mismatched wire compression: One rank requested "
                             f"wire dtype {wire0 or 'fp32'}, but another rank "
                             f"requested wire dtype {r.wire_dtype or 'fp32'}.")
                    break

        # The allreduce algorithm must be uniform for the same reason: hop
        # schedules differ per algorithm, so disagreeing ranks would
        # deadlock the data plane.  Coordinated error, like wire dtype.
        if error is None:
            algo0 = requests[0].algo
            for r in requests[1:]:
                if r.algo != algo0:
                    error = ("Mismatched allreduce algorithm: One rank "
                             f"requested algorithm {algo0 or 'ring'}, but "
                             "another rank requested algorithm "
                             f"{r.algo or 'ring'}.")
                    break

        message_type = requests[0].request_type
        if error is None:
            for r in requests[1:]:
                if r.request_type != message_type:
                    error = ("Mismatched MPI operations: One rank did an "
                             f"{request_type_name(message_type)}, but another "
                             f"rank did an {request_type_name(r.request_type)}.")
                    break

        if error is None and message_type in (RequestType.ALLREDUCE,
                                              RequestType.BROADCAST):
            shape0 = requests[0].tensor_shape
            for r in requests[1:]:
                if r.tensor_shape != shape0:
                    error = (f"Mismatched {request_type_name(message_type)} "
                             "tensor shapes: One rank sent a tensor of shape "
                             f"{shape_debug_string(shape0)}, but another rank "
                             "sent a tensor of shape "
                             f"{shape_debug_string(r.tensor_shape)}.")
                    break

        tensor_sizes = [0] * len(requests)
        if error is None and message_type == RequestType.ALLGATHER:
            shape0 = requests[0].tensor_shape
            if len(shape0) == 0:
                error = (f"Rank zero tried to {request_type_name(message_type)} "
                         "a rank-zero tensor.")
            else:
                tensor_sizes[requests[0].request_rank] = shape0[0]
                for r in requests[1:]:
                    shp = r.tensor_shape
                    if len(shp) != len(shape0):
                        error = (f"Mismatched {request_type_name(message_type)} "
                                 "tensor shapes: One rank sent a tensor of rank "
                                 f"{len(shape0)}, but another rank sent a tensor "
                                 f"of rank {len(shp)}.")
                        break
                    dim_mismatch = False
                    for dim in range(1, len(shape0)):
                        if shape0[dim] != shp[dim]:
                            error = (
                                f"Mismatched {request_type_name(message_type)} "
                                f"tensor shapes: One rank sent a tensor with "
                                f"dimension {dim} equal to {shape0[dim]}, but "
                                f"another rank sent a tensor with dimension "
                                f"{dim} equal to {shp[dim]}.")
                            dim_mismatch = True
                            break
                    if dim_mismatch:
                        break
                    tensor_sizes[r.request_rank] = shp[0]

        if error is None and message_type == RequestType.BROADCAST:
            root0 = requests[0].root_rank
            for r in requests[1:]:
                if r.root_rank != root0:
                    error = (f"Mismatched {request_type_name(message_type)} "
                             f"root ranks: One rank specified root rank "
                             f"{root0}, but another rank specified root rank "
                             f"{r.root_rank}.")
                    break

        # Device-placement consistency: every rank must agree on host (-1)
        # vs accelerator placement, mirroring the CPU-vs-GPU check in
        # ConstructMPIResponse (reference operations.cc:470-487).
        if error is None:
            first_is_host = requests[0].device < 0
            for r in requests[1:]:
                this_is_host = r.device < 0
                if this_is_host != first_is_host:
                    error = (f"Mismatched {request_type_name(message_type)} "
                             "CPU/TPU device selection: One rank specified "
                             f"device {'CPU' if first_is_host else 'TPU'}, "
                             "but another rank specified device "
                             f"{'CPU' if this_is_host else 'TPU'}.")
                    break

        devices = [0] * len(requests)
        for r in requests:
            devices[r.request_rank] = r.device

        del self._table[name]

        wire_dtype = requests[0].wire_dtype
        if error is not None:
            return Response(ResponseType.ERROR, [name], error_message=error,
                            devices=devices, wire_dtype=wire_dtype)
        if message_type == RequestType.ALLGATHER:
            return Response(ResponseType.ALLGATHER, [name],
                            tensor_sizes=tensor_sizes, devices=devices,
                            wire_dtype=wire_dtype)
        if message_type == RequestType.ALLREDUCE:
            # Resolve the (uniform) preference to a concrete algorithm by
            # this payload's size — the data plane never sees "auto".
            try:
                nbytes = np.dtype(data_type).itemsize
            except TypeError:
                nbytes = 0
            for d in requests[0].tensor_shape:
                nbytes *= d
            return Response(ResponseType.ALLREDUCE, [name], devices=devices,
                            wire_dtype=wire_dtype,
                            algo=self._resolve_algo(requests[0].algo, nbytes))
        return Response(ResponseType.BROADCAST, [name], devices=devices,
                        wire_dtype=wire_dtype)


# --------------------------------------------------------------------------
# Fusion planner (reference operations.cc:1807-1842)
# --------------------------------------------------------------------------

DEFAULT_FUSION_THRESHOLD = 64 * 1024 * 1024   # bytes (operations.cc:151)
FUSION_BUFFER_ATOMIC_UNIT = 64                # bytes (operations.h:48-50)


def plan_fusion(responses: List[Response],
                entry_bytes: Callable[[str], int],
                entry_dtype: Callable[[str], str],
                threshold: int) -> List[Response]:
    """Greedily merge consecutive ALLREDUCE responses of the same dtype while
    the combined payload stays ≤ ``threshold`` bytes.

    Mirrors the coordinator's fusion loop (``operations.cc:1807-1842``):
    only allreduces fuse; a threshold of 0 disables fusion.
    """
    fused: List[Response] = []
    i = 0
    while i < len(responses):
        r = responses[i]
        if r.response_type != ResponseType.ALLREDUCE or threshold <= 0:
            fused.append(r)
            i += 1
            continue
        names = list(r.tensor_names)
        total = sum(entry_bytes(n) for n in names)
        dtype = entry_dtype(names[0])
        j = i + 1
        while j < len(responses):
            nxt = responses[j]
            if nxt.response_type != ResponseType.ALLREDUCE:
                break
            nbytes = sum(entry_bytes(n) for n in nxt.tensor_names)
            if entry_dtype(nxt.tensor_names[0]) != dtype:
                break
            # A fused buffer rides the ring as one payload with one wire
            # format — only merge entries that negotiated the same one.
            if nxt.wire_dtype != r.wire_dtype:
                break
            # Likewise one collective algorithm per fused payload: the
            # data plane walks a single hop schedule for the whole buffer.
            if nxt.algo != r.algo:
                break
            if total + nbytes > threshold:
                break
            names.extend(nxt.tensor_names)
            total += nbytes
            j += 1
        fused.append(Response(ResponseType.ALLREDUCE, names,
                              devices=r.devices, wire_dtype=r.wire_dtype,
                              algo=r.algo))
        i = j
    return fused


# --------------------------------------------------------------------------
# Handle manager (reference horovod/torch/handle_manager.{h,cc})
# --------------------------------------------------------------------------

DEFAULT_OP_TIMEOUT_S = 600.0


def default_op_timeout() -> Optional[float]:
    """Deadline for HandleManager.wait when the caller passes no timeout
    (HOROVOD_TPU_OP_TIMEOUT_S; <= 0 restores the old infinite wait)."""
    t = float(os.environ.get("HOROVOD_TPU_OP_TIMEOUT_S",
                             str(DEFAULT_OP_TIMEOUT_S)))
    return t if t > 0 else None


class HandleManager:
    """Thread-safe int-handle → Status map for async ops."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._next = 0
        self._results: Dict[int, Optional[Tuple[Status, object]]] = {}
        # Handles whose payload may launch programs on a shared mesh
        # runtime (everything except host-path 64-bit dtypes) — the set
        # the ordering guard counts.
        self._mesh_hazard: set = set()
        # Op name per live handle, for wait-timeout diagnostics.
        self._names: Dict[int, str] = {}

    def allocate(self, mesh_hazard: bool = False, name: str = "") -> int:
        with self._lock:
            h = self._next
            self._next += 1
            self._results[h] = None
            if mesh_hazard:
                self._mesh_hazard.add(h)
            if name:
                self._names[h] = name
            return h

    def mark_done(self, handle: int, status: Status, result=None) -> None:
        with self._cv:
            # No-op for unknown handles — covers results arriving after the
            # caller abandoned a timed-out handle.
            if handle in self._results:
                self._results[handle] = (status, result)
                self._mesh_hazard.discard(handle)
                self._cv.notify_all()

    def abandon(self, handle: int) -> None:
        """Give up on a handle: drop it now; a completion arriving later
        hits the unknown-handle no-op in ``mark_done`` and is discarded."""
        with self._lock:
            self._results.pop(handle, None)
            self._mesh_hazard.discard(handle)
            self._names.pop(handle, None)

    def poll(self, handle: int) -> bool:
        with self._lock:
            self._check_known(handle)
            return self._results[handle] is not None

    def wait(self, handle: int, timeout: Optional[float] = None):
        """Block until the handle completes.

        ``timeout=None`` no longer means "wait forever": it resolves to the
        HOROVOD_TPU_OP_TIMEOUT_S deadline (default 600 s; <= 0 restores the
        infinite wait).  On that default deadline the handle is ABANDONED
        (a late completion is discarded by mark_done's unknown-handle
        no-op) and a TimeoutError naming the op is raised — a wedged
        collective surfaces as a diagnosable error instead of a silent
        hang.  An explicit caller-supplied timeout keeps the old contract:
        TimeoutError without abandoning, so the caller decides.
        """
        abandon_on_timeout = False
        if timeout is None:
            timeout = default_op_timeout()
            abandon_on_timeout = timeout is not None
        t0 = time.monotonic()
        try:
            with self._cv:
                self._check_known(handle)
                if not self._cv.wait_for(
                        lambda: self._results[handle] is not None, timeout):
                    name = self._names.get(handle, "")
                    op = f" (op '{name}')" if name else ""
                    # Capture the last N ticks of control/transport events
                    # before abandoning: a wedged collective is exactly the
                    # moment post-hoc state is needed and live inspection is
                    # impossible.
                    from horovod_tpu import cpp_core
                    cpp_core.flight_record("op.timeout", name, 0, handle,
                                           int(timeout or 0))
                    flight = cpp_core.flight_dump("op_timeout")
                    flight_note = (f" [flight recorder: {flight}]"
                                   if flight else "")
                    if abandon_on_timeout:
                        self._results.pop(handle, None)
                        self._mesh_hazard.discard(handle)
                        self._names.pop(handle, None)
                        raise TimeoutError(
                            f"handle {handle}{op} did not complete within "
                            f"{timeout:.0f}s (HOROVOD_TPU_OP_TIMEOUT_S); the "
                            "handle has been abandoned. A peer rank likely "
                            "never submitted this collective — check for "
                            "stall warnings on rank 0." + flight_note)
                    raise TimeoutError(
                        f"handle {handle}{op} did not complete" + flight_note)
                return self._results[handle]
        finally:
            # Time-to-result from the framework thread's point of view —
            # recorded on timeouts too, so stalls show in the tail.
            _metrics.registry.observe("controller.handle_wait_seconds",
                                      time.monotonic() - t0)

    def release(self, handle: int):
        with self._lock:
            self._results.pop(handle, None)
            self._mesh_hazard.discard(handle)
            self._names.pop(handle, None)

    def outstanding(self) -> int:
        """Handles allocated but not yet completed (still in flight)."""
        with self._lock:
            return sum(1 for v in self._results.values() if v is None)

    def outstanding_mesh_hazard(self) -> int:
        """In-flight handles flagged as possibly launching mesh programs
        (host-path 64-bit ops are excluded — they never touch the shared
        runtime, so dispatching jitted steps around them is safe)."""
        with self._lock:
            return sum(1 for h in self._mesh_hazard
                       if self._results.get(h) is None)

    def _check_known(self, handle: int):
        if handle not in self._results:
            raise ValueError(f"unknown handle: {handle}")


# --------------------------------------------------------------------------
# Tensor table entry + controller
# --------------------------------------------------------------------------

@dataclasses.dataclass
class TensorTableEntry:
    """Tensor data + callback held while a collective is in flight
    (reference ``TensorTableEntry``, ``operations.cc:60-100``)."""
    name: str
    request_type: RequestType
    # One contribution per participating rank this process controls.  In the
    # single-controller SPMD model a process enqueues on behalf of all its
    # local ranks at once: either a replicated array (same value per rank) or
    # an explicit per-rank list.
    per_rank: List[np.ndarray]
    dtype: str
    root_rank: int
    average: bool
    callback: Callable[[Status, object], None]
    # Ring wire compression for the cross-process data plane ("" = raw
    # fp32; "bf16"/"fp16"/"int8").  Negotiated across ranks like dtype.
    wire_dtype: str = ""
    # Process set this entry negotiates in (0 = default/world).  Set
    # entries hold one contribution per MEMBER rank this process controls
    # and execute on the set-scoped host path.
    process_set: int = 0


def cache_capacity_from_env() -> int:
    """HOROVOD_TPU_CACHE_CAPACITY: response-cache slots (default 1024;
    0 disables the cache entirely).  Malformed values fall back to the
    default — same leniency as the native parser in control.cc."""
    raw = os.environ.get("HOROVOD_TPU_CACHE_CAPACITY", "")
    try:
        v = int(raw)
        return v if v >= 0 else 1024
    except ValueError:
        return 1024


class _LocalResponseCache:
    """Single-process half of the negotiation response cache.

    The multi-process cache lives inside the native control plane
    (cpp/htpu: bitvector ticks on the wire); this class gives the local
    loop the same skip: a tick whose pending request batch serializes
    byte-identically to an earlier fully-successful tick replays that
    tick's fused responses without touching the MessageTable or the
    fusion planner.  Replay is bit-identical by construction — the stored
    responses ARE the ones the uncached path built, handed out as fresh
    copies.  Shape / dtype / wire-dtype changes alter the serialized
    batch, so they miss naturally and the stale entry ages out by LRU.
    """

    # Full response sets kept per distinct batch shape; small — steady
    # training loops replay one or two shapes (matches the native client's
    # cache_set_ bound).
    MAX_SETS = 16

    def __init__(self, capacity: int):
        self.capacity = capacity
        # name -> serialized request group (byte-exact per-name hit test,
        # LRU-bounded by `capacity` for knob parity with the native cache).
        self._names: "collections.OrderedDict[str, bytes]" = \
            collections.OrderedDict()
        # batch key -> fused response list of the tick that negotiated it.
        self._sets: "collections.OrderedDict[bytes, List[Response]]" = \
            collections.OrderedDict()

    @staticmethod
    def _batch_key(pending: List[Request]) -> bytes:
        from horovod_tpu import wire
        # with_algo so an algorithm-preference change misses (and the
        # replayed responses keep their resolved algo) — matches the
        # native cache's signature (control.cc CompressRequestFrame).
        return b"".join(
            wire.serialize_request(r, with_algo=True) for r in pending)

    def _account(self, pending: List[Request]) -> None:
        """Per-name hit/miss/eviction metrics, mirroring the native
        counters (control.cache_hits / _misses / _evictions)."""
        from horovod_tpu import wire
        groups: "collections.OrderedDict[str, bytes]" = \
            collections.OrderedDict()
        for r in pending:
            groups[r.tensor_name] = (groups.get(r.tensor_name, b"")
                                     + wire.serialize_request(
                                         r, with_algo=True))
        hits = misses = 0
        for name, sig in groups.items():
            if self._names.get(name) == sig:
                hits += 1
                self._names.move_to_end(name)
            else:
                misses += 1
                self._names[name] = sig
                self._names.move_to_end(name)
        evicted = 0
        while len(self._names) > self.capacity:
            self._names.popitem(last=False)
            evicted += 1
        _metrics.registry.inc("control.cache_hits", hits)
        _metrics.registry.inc("control.cache_misses", misses)
        if evicted:
            _metrics.registry.inc("control.cache_evictions", evicted)

    def lookup(self, pending: List[Request],
               table_empty: bool) -> Optional[List[Response]]:
        """Fused responses to replay for this batch, or None to negotiate
        in full.  Replay requires an empty message table: a stored set
        only equals the uncached result when no straggler from an earlier
        tick could have contributed to it."""
        if self.capacity <= 0 or not pending:
            return None
        self._account(pending)
        if not table_empty:
            return None
        stored = self._sets.get(self._batch_key(pending))
        if stored is None:
            return None
        self._sets.move_to_end(self._batch_key(pending))
        return [dataclasses.replace(
                    r, tensor_names=list(r.tensor_names),
                    devices=list(r.devices),
                    tensor_sizes=list(r.tensor_sizes))
                for r in stored]

    def store(self, pending: List[Request], fused: List[Response]) -> None:
        """Record a fully-successful tick (every pending name constructed,
        no ERROR responses, table drained) for later replay."""
        if self.capacity <= 0:
            return
        key = self._batch_key(pending)
        self._sets[key] = [dataclasses.replace(
                               r, tensor_names=list(r.tensor_names),
                               devices=list(r.devices),
                               tensor_sizes=list(r.tensor_sizes))
                           for r in fused]
        self._sets.move_to_end(key)
        while len(self._sets) > self.MAX_SETS:
            self._sets.popitem(last=False)

    def flush(self) -> None:
        """Abort/restart: drop everything (counted as evictions, like the
        native cache's flush)."""
        if self._names:
            _metrics.registry.inc("control.cache_evictions",
                                  len(self._names))
        self._names.clear()
        self._sets.clear()


class Controller:
    """Per-process background controller.

    Owns: message queue (framework threads push), tensor table, message
    table (negotiation), fusion planner, stall checker, timeline, handle
    manager, and the data-plane executor.  One daemon thread runs
    ``_run_loop_once`` every ``cycle_time`` — the reference's
    ``RunLoopOnce`` tick (``operations.cc:1694-1903``).
    """

    def __init__(self, topology, mesh):
        self.topology = topology
        self.mesh = mesh
        self.size = topology.size
        self.cycle_time_s = float(
            os.environ.get("HOROVOD_TPU_CYCLE_TIME_MS", "1.0")) / 1e3
        self.fusion_threshold = int(
            os.environ.get("HOROVOD_TPU_FUSION_THRESHOLD",
                           str(DEFAULT_FUSION_THRESHOLD)))
        self.stall_warning_time_s = 60.0
        self.stall_check_disabled = env_flag(
            "HOROVOD_TPU_STALL_CHECK_DISABLE")

        # Fail fast on malformed fault specs: the native core parses the
        # same variable leniently (warn + ignore), which would make a typo'd
        # injection test silently pass.  The parsed specs are kept for the
        # Python-owned injections (the local loop's `slow` straggler).
        self._fault_specs = parse_fault_specs(
            os.environ.get("HOROVOD_TPU_FAULT", ""))
        self._fault_tick = 0
        self._slow_announced: set = set()

        # Native core (cpp/htpu): message table, fusion planner and timeline
        # run in C++ when the shared library is available; the Python classes
        # below remain the executable specification and fallback.
        from horovod_tpu import cpp_core
        self._use_cpp = cpp_core.available()

        # Multi-process mode: negotiation + eager data plane ride the native
        # TCP control plane (reference: MPI gather/bcast + CPU data plane).
        self._control = None
        self._rank_to_process: Dict[int, int] = {}
        # Host grouping (None = not discovered; single-process jobs don't
        # need it — one process per host is the TPU pod norm).
        self.host_local_rank: Optional[int] = None
        self.host_local_size: Optional[int] = None
        # Distinct host count across the job (refined by the control-plane
        # layout exchange below); feeds allreduce algorithm selection.
        self.num_hosts = 1
        coord_addr = os.environ.get("HOROVOD_TPU_COORD_ADDR", "")
        # Multi-controller pod with no control plane configured: jit-only
        # mode.  The SPMD path needs no negotiation (XLA's runtime carries
        # the in-jit collectives); the eager API is unavailable and fails
        # fast at enqueue() instead of stall-deadlocking (each process
        # would submit only its local ranks while `size` spans the pod).
        self.jit_only = topology.process_count > 1 and not coord_addr
        if coord_addr and topology.process_count > 1:
            if not self._use_cpp:
                raise RuntimeError(
                    "multi-process mode requires the native core "
                    "(unset HOROVOD_TPU_NO_CPP)")
            host, _, port = coord_addr.rpartition(":")
            timeout_ms = int(float(os.environ.get(
                "HOROVOD_TPU_CONTROL_TIMEOUT_S", "60")) * 1000)
            self._control = cpp_core.CppControlPlane(
                topology.process_index, topology.process_count,
                host or "127.0.0.1", int(port), topology.rank,
                topology.size, timeout_ms)
            if (os.environ.get("HOROVOD_TPU_STANDBY") == "1"
                    and self._control.elastic()):
                # Admitted standby: the native Create() blocked until the
                # elastic coordinator seated this process into a live
                # generation — adopt the identity it assigned.  The
                # init-time layout exchange below is impossible here (the
                # survivors are mid-training, not parked in an init
                # collective), so the rank map comes from the dense
                # re-rank arithmetic elastic mode guarantees.
                pidx, pcount, first_rank, generation = (
                    self._control.membership())
                lsize = topology.local_size
                topology = dataclasses.replace(
                    topology, process_index=pidx, process_count=pcount,
                    rank_override=first_rank,
                    size_override=pcount * lsize)
                self.topology = topology
                self.size = topology.size
                for r in range(pcount * lsize):
                    self._rank_to_process[r] = r // lsize
                _metrics.registry.set_gauge("membership.generation",
                                            generation)
                print(f"horovod_tpu elastic: standby admitted at "
                      f"generation {generation} as rank {first_rank} "
                      f"of {topology.size} (process {pidx} of {pcount})",
                      file=sys.stderr)
            else:
                # Exchange the process layout once: (process_index,
                # first_rank, local_size, host fingerprint) per process ->
                # global rank->process map plus host grouping (the
                # reference gets both from MPI comm splits,
                # operations.cc:1499-1532; boot-id fingerprint equality is
                # the TPU-native stand-in for MPI_Comm_split_type(SHARED)
                # — hostname alone is ambiguous, see
                # topology.host_fingerprint).
                import struct
                from horovod_tpu.topology import host_fingerprint
                my_host = host_fingerprint(warn_truncation=True).encode()[:64]
                mine = struct.pack("<3i64s", topology.process_index,
                                   topology.rank, topology.local_size,
                                   my_host)
                blob = self._control.allgather(mine)
                host_procs = []
                all_hosts = set()
                for off in range(0, len(blob), 76):
                    pidx, frank, lsize, host = struct.unpack_from(
                        "<3i64s", blob, off)
                    for r in range(frank, frank + lsize):
                        self._rank_to_process[r] = pidx
                    all_hosts.add(host.rstrip(b"\0"))
                    if host.rstrip(b"\0") == my_host.rstrip(b"\0"):
                        host_procs.append(pidx)
                host_procs.sort()
                self.host_local_rank = host_procs.index(
                    topology.process_index)
                self.host_local_size = len(host_procs)
                self.num_hosts = len(all_hosts)
        elif self.jit_only:
            # Host grouping without a control plane: the only cross-process
            # channel in jit-only mode is XLA itself, so allgather each
            # process's host-fingerprint hash over the pod runtime.  Without
            # this, every co-located process would silently report
            # local_rank() == 0 and collide on per-host work (the reference
            # gets the grouping from MPI_Comm_split_type(SHARED)).
            import hashlib
            from jax.experimental import multihost_utils
            from horovod_tpu.topology import host_fingerprint
            digest = hashlib.sha256(host_fingerprint().encode()).digest()
            mine = np.concatenate([
                np.asarray([topology.process_index], np.uint32),
                np.frombuffer(digest[:8], np.uint32)])
            # Bounded like the control-plane exchange: if a peer never
            # reaches init() (crash, rank-subset mismatch) the collective
            # would otherwise hang every healthy process forever with no
            # diagnostic.  The watchdog thread is leaked on timeout — the
            # process is about to raise out of init() anyway.
            timeout_s = float(os.environ.get(
                "HOROVOD_TPU_CONTROL_TIMEOUT_S", "60"))
            result: list = []

            def _gather():
                try:
                    result.append(("ok", np.asarray(
                        multihost_utils.process_allgather(mine))))
                except BaseException as exc:   # noqa: BLE001 — re-raised
                    result.append(("err", exc))

            th = threading.Thread(target=_gather, daemon=True,
                                  name="horovod_tpu-host-discovery")
            th.start()
            th.join(timeout_s)
            if not result:
                raise RuntimeError(
                    f"horovod_tpu: host-grouping allgather did not complete "
                    f"within {timeout_s:.0f}s — some process in this "
                    f"{topology.process_count}-process job never reached "
                    "hvd.init() (init is collective across processes). "
                    "Raise HOROVOD_TPU_CONTROL_TIMEOUT_S if startup is "
                    "legitimately slow.")
            if result[0][0] == "err":
                raise result[0][1]
            rows = result[0][1]
            host_procs = sorted(
                int(r[0]) for r in rows
                if r[1] == mine[1] and r[2] == mine[2])
            self.host_local_rank = host_procs.index(topology.process_index)
            self.host_local_size = len(host_procs)

        self.timeline = None
        timeline_path = os.environ.get("HOROVOD_TPU_TIMELINE", "")
        if timeline_path:
            # Every rank traces (the reference traces only the
            # coordinator; per-rank traces are what trace_merge.py and
            # straggler attribution feed on).  The env value is a path
            # template — resolve this rank's file from it.  Idempotent
            # when run.py already filled it in for this child.
            from horovod_tpu.timeline import per_rank_trace_path
            rank_path = per_rank_trace_path(
                timeline_path, topology.rank, topology.size)
            if self._use_cpp:
                self.timeline = cpp_core.CppTimeline(
                    rank_path, topology.rank)
            else:
                from horovod_tpu.timeline import Timeline
                self.timeline = Timeline(rank_path, topology.rank)
        if (self._control is not None and self.timeline is not None
                and hasattr(self.timeline, "attach_to_control")):
            # Multi-process mode negotiates inside the C++ coordinator;
            # wire the native timeline in so NEGOTIATE_* spans (with
            # per-rank ready instants) appear exactly as in the
            # single-process mode (reference timeline model, §5.1).
            self.timeline.attach_to_control(self._control)
        if self.timeline is not None:
            # Durability guard: a process that dies without shutdown()
            # (uncaught exception, sys.exit in user code) still gets its
            # trace closed into loadable JSON.  close() is idempotent, so
            # the normal stop() path is unaffected.
            import atexit
            atexit.register(self._close_timeline)

        self.handle_manager = HandleManager()
        # Both planners route through the plane-agnostic scheduler's
        # per-tick policy (fusion + first-ready issue order); the native
        # cpp_plan_tick degrades to cpp_plan_fusion on a stale library.
        if self._use_cpp:
            self._message_table = cpp_core.CppMessageTable(
                self.size, self.timeline)
            self._plan_fusion = cpp_core.cpp_plan_tick
        else:
            self._message_table = MessageTable(self.size, self.timeline)
            from . import scheduler as _scheduler
            self._plan_fusion = _scheduler.plan_tick
        # Topology + crossover for "auto" algorithm resolution.  The native
        # control plane configures its own internal table the same way
        # (control.cc Create); this covers the local negotiation loop.
        self._message_table.configure_algo_selection(
            self.num_hosts, topology.process_count, algo_crossover_bytes())
        # Response cache for the single-process negotiation loop.  The
        # multi-process equivalent lives inside the native control plane's
        # Tick (bitvector wire ticks), so the Python cache stays off there
        # — the two never double-count metrics.
        self._local_cache = None
        if self._control is None and not self.jit_only:
            capacity = cache_capacity_from_env()
            if capacity > 0:
                self._local_cache = _LocalResponseCache(capacity)
        # Non-default process sets (multi-tenant negotiation namespaces):
        # the registry owns each set's scoped MessageTable + cache; the
        # controller only routes by ``entry.process_set``.  Seeded from
        # HOROVOD_TPU_PROCESS_SETS so ids agree with the native
        # coordinator, which parses the same spec (control.cc Create).
        from horovod_tpu import process_set as _process_set_mod
        self._process_sets = _process_set_mod.registry()
        self._tensor_table: Dict[str, TensorTableEntry] = {}
        self._message_queue: collections.deque = collections.deque()
        self._lock = threading.Lock()
        self._shutdown = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_stall_check = time.monotonic()
        # Stall-warning dedupe: name -> frozenset(missing ranks) at the
        # last warning.  A tensor re-warns only when its missing-rank set
        # changes; resolved names drop out on the next check.
        self._stall_warned: Dict[str, frozenset] = {}
        # Last timeline counter-track values — counter events are emitted
        # only on change so idle ticks don't bloat the trace.
        self._last_counters: Dict[str, int] = {}
        # Job-wide abort latch.  Once set, every outstanding handle has
        # completed with this ABORTED status and enqueue() fails fast with
        # the same attributed cause (no new work can strand a waiter).
        self._abort_status: Optional[Status] = None
        # Failure observed locally (native data-plane error) waiting to ride
        # the next tick's request list to the coordinator, which turns it
        # into the job-wide ABORT broadcast.
        self._pending_report: Optional[Tuple[int, str]] = None
        self._last_reported: Optional[Tuple[int, str]] = None

        if self._control is not None:
            from horovod_tpu.ops.executor import DistributedExecutor
            self._executor = DistributedExecutor(
                topology, mesh, self.timeline, self._control,
                self._rank_to_process)
        else:
            from horovod_tpu.ops.executor import Executor
            self._executor = Executor(topology, mesh, self.timeline)

    # ------------------------------------------------------------------ API

    def mesh_async_hazard(self) -> int:
        """Outstanding async eager handles whose collective programs ride
        the SHARED multi-controller runtime — the count that makes
        launching another jitted collective program unsafe (each process
        could interleave the background programs differently; the
        ordering invariant the reference's coordinator enforces,
        ``operations.cc:1414-1433``).  0 on disjoint runtimes (TCP data
        plane) and single-process jobs, where background execution is
        process-local."""
        ex = getattr(self, "_executor", None)
        if ex is None or not getattr(ex, "_mesh_is_global", False):
            return 0
        return self.handle_manager.outstanding_mesh_hazard()

    def start(self):
        if self.jit_only:
            # No negotiation to run: the background tick loop exists only
            # for the eager data plane, which is gated off in this mode.
            return
        self._thread = threading.Thread(
            target=self._background_loop, name="horovod_tpu-controller",
            daemon=True)
        self._thread.start()

    def stop(self):
        """Coordinated shutdown: outstanding entries get SHUT_DOWN_ERROR
        (reference ``operations.cc:1647-1662``).  In multi-process mode the
        shutdown flag rides the next request list, so every process exits
        its loop together (``operations.cc:1780-1784, 1896-1899``)."""
        with self._lock:
            self._shutdown.set()
        thread_exited = True
        if self._thread is not None:
            self._thread.join(timeout=90.0)
            thread_exited = not self._thread.is_alive()
            self._thread = None
        with self._lock:
            entries = list(self._tensor_table.values())
            self._tensor_table.clear()
            self._message_queue.clear()
        for e in entries:
            e.callback(SHUT_DOWN_ERROR, None)
        if self._control is not None and not thread_exited:
            # The background thread is wedged inside a control-plane call
            # (e.g. a dead peer): destroying the native objects under it
            # would be a use-after-free — leak them instead (the wrappers'
            # __del__ would otherwise still destroy at GC); the process
            # is tearing down anyway.  The control plane holds a raw
            # pointer to the native timeline, so both leak together.
            if hasattr(self._control, "leak"):
                self._control.leak()
            if self.timeline and hasattr(self.timeline, "leak"):
                self.timeline.leak()
        else:
            if self._control is not None:
                self._control.close()
            if self.timeline:
                self.timeline.close()

    def enqueue(self, entry: TensorTableEntry) -> Status:
        """Framework-thread side: register tensor data and queue one request
        per controlled rank (reference ``EnqueueTensorAllreduce`` et al.,
        ``operations.cc:2025-2141``)."""
        if self.jit_only:
            return Status.precondition_error(
                f"horovod_tpu: eager collective '{entry.name}' needs the "
                f"TCP control plane, but this job spans "
                f"{self.topology.process_count} processes with none "
                "configured (jit-only mode). The in-jit SPMD path "
                "(make_train_step, horovod_tpu.ops.injit, the global mesh) "
                "works without it. For eager collectives, launch with "
                "`python -m horovod_tpu.run -np <N> ...` or export "
                "HOROVOD_TPU_COORD_ADDR=<host>:<port> plus "
                "HOROVOD_TPU_{SIZE,RANK,PROCESS_INDEX,PROCESS_COUNT} on "
                "every process; see docs/running.md.")
        first_rank = self.topology.rank
        # Allreduces carry the process-wide algorithm preference (read per
        # enqueue so HOROVOD_TPU_ALLREDUCE_ALGO changes take effect without
        # reinit); other collectives have a single data-plane path.
        algo = (default_allreduce_algo()
                if entry.request_type == RequestType.ALLREDUCE else "")
        requests: List[Request] = []
        if entry.process_set:
            err = self._build_set_requests(entry, algo, requests)
            if err is not None:
                return err
        else:
            for i, contrib in enumerate(entry.per_rank):
                requests.append(Request(
                    request_rank=first_rank + i,
                    request_type=entry.request_type,
                    tensor_name=entry.name,
                    tensor_type=np.dtype(contrib.dtype).name,
                    tensor_shape=tuple(contrib.shape),
                    root_rank=entry.root_rank,
                    device=first_rank + i,
                    wire_dtype=entry.wire_dtype,
                    algo=algo,
                ))
        with self._lock:
            # Abort outranks plain shutdown: after a job-wide abort every
            # enqueue fails fast with the ORIGINAL attributed cause, not the
            # generic shut-down text.
            if self._abort_status is not None:
                return self._abort_status
            # Shutdown is checked under the same lock stop() takes while
            # draining, so an entry can never land in a dead controller.
            if self._shutdown.is_set():
                return SHUT_DOWN_ERROR
            if entry.name in self._tensor_table:
                return Status.invalid_argument(
                    f"Duplicate tensor name in queue: {entry.name}. "
                    "A collective for this tensor is already in progress.")
            self._tensor_table[entry.name] = entry
            self._message_queue.extend(requests)
        _metrics.registry.inc(
            "controller.enqueued#type="
            f"{request_type_name(entry.request_type).lower()},"
            f"dtype={entry.dtype}", len(requests))
        return Status.OK()

    def _build_set_requests(self, entry: TensorTableEntry, algo: str,
                            requests: List[Request]) -> Optional[Status]:
        """Requests for a non-default process set: SET-LOCAL request_rank,
        global rank in ``device`` (so the coordinator's per-set table —
        sized to the set — indexes correctly while frames stay globally
        attributable).  Returns an error Status, or None on success."""
        ps = self._process_sets.get(entry.process_set)
        if ps is None:
            return Status.invalid_argument(
                f"Unknown process set id {entry.process_set} for tensor "
                f"{entry.name}: register it with hvd.add_process_set() or "
                "HOROVOD_TPU_PROCESS_SETS (see docs/process-sets.md).")
        first = self.topology.rank
        controlled = range(first, first + self.topology.local_size)
        members = [g for g in ps.ranks if g in controlled]
        if len(members) != ps.size():
            # The set-scoped eager data plane is process-local: execution
            # reduces the member contributions this process holds, so a
            # set spanning processes would silently compute a partial
            # result — fail fast instead.
            return Status.precondition_error(
                f"process set '{ps.name}' spans ranks {list(ps.ranks)} "
                f"but this process controls only ranks "
                f"{list(controlled)}: every member rank of a set must "
                "live on one process — the set-scoped eager data plane "
                "is process-local (see docs/process-sets.md).")
        if len(entry.per_rank) != len(members):
            return Status.invalid_argument(
                f"process set '{ps.name}' needs {len(members)} "
                f"contributions (one per member rank), got "
                f"{len(entry.per_rank)}")
        for g, contrib in zip(members, entry.per_rank):
            requests.append(Request(
                request_rank=ps.local_rank(g),
                request_type=entry.request_type,
                tensor_name=entry.name,
                tensor_type=np.dtype(contrib.dtype).name,
                tensor_shape=tuple(contrib.shape),
                root_rank=entry.root_rank,
                device=g,
                wire_dtype=entry.wire_dtype,
                algo=algo,
                process_set=ps.id,
            ))
        return None

    # ------------------------------------------------------- background loop

    def _background_loop(self):
        if self._control is not None:
            self._background_loop_distributed()
            return
        while not self._shutdown.is_set():
            t0 = time.monotonic()
            try:
                self._run_loop_once()
            except Exception as exc:   # noqa: BLE001 — fail entries, not thread
                self._fail_all(Status(StatusType.UNKNOWN_ERROR, repr(exc)))
            elapsed = time.monotonic() - t0
            remaining = self.cycle_time_s - elapsed
            if remaining > 0:
                self._shutdown.wait(remaining)

    def _background_loop_distributed(self):
        """Multi-process tick loop.  Unlike the local loop, the final tick
        after ``_shutdown`` is set still runs — it carries the shutdown flag
        to the coordinator so every process exits together."""
        while True:
            t0 = time.monotonic()
            shutting = self._shutdown.is_set()
            try:
                remote_shutdown = self._run_loop_once_distributed(shutting)
            except Exception as exc:   # noqa: BLE001
                # The tick loop is dying — without it every later enqueue
                # fails with the generic shut-down text, so name the real
                # cause here (outstanding entries get it attributed too).
                traceback.print_exc()
                print(f"horovod_tpu: control tick loop failed: {exc!r}",
                      file=sys.stderr)
                self._fail_all(Status(StatusType.UNKNOWN_ERROR, repr(exc)))
                self._shutdown.set()
                return
            if shutting or remote_shutdown:
                if remote_shutdown and not shutting:
                    # Another process shut down; fail outstanding work here
                    # (stop() may never be called locally).
                    self._shutdown.set()
                    self._fail_all(SHUT_DOWN_ERROR)
                return
            elapsed = time.monotonic() - t0
            remaining = self.cycle_time_s - elapsed
            if remaining > 0:
                self._shutdown.wait(remaining)

    def _run_loop_once_distributed(self, shutting: bool) -> bool:
        """One negotiation tick over the TCP control plane; returns True if
        the coordinator announced job shutdown (or the job aborted)."""
        from horovod_tpu import wire
        with self._lock:
            pending = list(self._message_queue)
            self._message_queue.clear()
            report = self._pending_report
            self._pending_report = None
        abort_rank, abort_reason = report if report is not None else (-1, "")
        if pending:
            # Flight-recorder breadcrumb naming what this rank is about to
            # negotiate: an abort dump then shows WHICH tensors were in
            # flight on the stalled tick, not just that a tick stalled.
            from horovod_tpu import cpp_core
            names = ",".join(r.tensor_name for r in pending[:4])
            if len(pending) > 4:
                names += f",+{len(pending) - 4}"
            cpp_core.flight_record("negotiate.pending", names,
                                   0, len(pending))
            # Per-tenant request accounting (the local loop's analogue
            # lives in _negotiate_sets; the coordinator adds its own
            # control.negotiate_seconds#process_set= series natively).
            for r in pending:
                if r.process_set:
                    ps = self._process_sets.get(r.process_set)
                    tag = ps.name if ps is not None else str(r.process_set)
                    _metrics.registry.inc(
                        f"control.set_requests#process_set={tag}")
        precision_ext = None
        if not shutting:
            # Adaptive-precision autopilot: piggyback the residual-norm
            # reports measured since the last tick onto this request frame
            # (FLAG_PRECISION_EXT).  Off (the default) contributes no
            # bytes — frames stay byte-identical to pre-autopilot builds.
            from horovod_tpu import precision as _precision
            pilot = _precision.get_autopilot()
            if pilot.enabled:
                reports = pilot.drain_reports()
                if reports:
                    precision_ext = wire.RequestPrecisionExt(reports=reports)
        blob = wire.serialize_request_list(
            pending, shutdown=shutting,
            abort_rank=abort_rank, abort_reason=abort_reason,
            precision_ext=precision_ext)
        resp_blob = self._control.tick(blob, self.fusion_threshold)
        (responses, remote_shutdown, abort, _cache_ext,
         elastic_ext) = wire.parse_response_list_elastic(resp_blob)
        if abort is not None:
            # Coordinator-broadcast ABORT (or a locally synthesized one when
            # the coordinator link itself died).  Latch, fail everything
            # with the attributed cause, and leave the tick loop.
            self._handle_abort(*abort)
            return True
        if elastic_ext is not None and elastic_ext.reconfigure:
            # Membership change (RECONFIGURE broadcast).  The native plane
            # already re-ranked and re-bootstrapped inside Tick; adopt the
            # new identity and KEEP ticking — survivors resume, they don't
            # abort.
            self._handle_reconfigure(elastic_ext)
            return False
        ready = []
        for resp in responses:
            with self._lock:
                # Pop only entries whose process set matches: two tenants
                # reusing a tensor name must never cross-execute (the
                # coordinator stamps set responses, wire FLAG_SET_EXT).
                entries = [self._tensor_table.pop(n)
                           for n in resp.tensor_names
                           if n in self._tensor_table
                           and (self._tensor_table[n].process_set
                                == resp.process_set)]
            if entries:
                ready.append((resp, entries))
        if self.timeline:
            # QUEUE: response constructed → executor picks it up (the
            # reference brackets the same wait, operations.h:35 +
            # operations.cc:951 — later responses in one tick queue
            # behind earlier ones executing).
            for _, entries in ready:
                self.timeline.activity_start_all(entries, "QUEUE")
        self._execute_ready(ready)
        self._maybe_check_stalls_distributed()
        self._tick_telemetry()
        return remote_shutdown

    def _execute_ready(self, ready):
        """Run each popped (response, entries) pair; a raising executor
        (normally impossible — execute converts failures to ERROR
        callbacks) must not strand the LATER responses' already-popped
        entries: their callbacks would never fire and no stall scan could
        see them, so convert the failure and keep going."""
        for resp, entries in ready:
            _metrics.registry.inc(
                "controller.ops#type="
                + ResponseType(resp.response_type).name.lower())
            if (resp.response_type == ResponseType.ALLREDUCE
                    and resp.process_set == 0
                    and self.fusion_threshold > 0 and entries):
                nbytes = sum(int(e.per_rank[0].nbytes) for e in entries)
                _metrics.registry.observe(
                    "controller.fusion_fill_ratio",
                    min(1.0, nbytes / self.fusion_threshold),
                    bounds=_metrics.RATIO_BOUNDS)
            if self.timeline:
                self.timeline.activity_end_all(entries)
            try:
                if resp.process_set:
                    self._execute_set(resp, entries)
                else:
                    self._executor.execute(resp, entries)
            except Exception as exc:   # noqa: BLE001 — see docstring
                status = Status(StatusType.UNKNOWN_ERROR, repr(exc))
                for e in entries:
                    try:
                        e.callback(status, None)
                    except Exception:   # noqa: BLE001 — best-effort
                        pass
        if ready and self._control is not None:
            self._note_data_plane_failure()

    def _note_data_plane_failure(self):
        """Pick up a native ring data-plane failure recorded by the C++ core
        (attributed to the ring neighbour whose socket died) and queue it to
        ride the next tick's request list; the coordinator converts the
        report into the job-wide ABORT broadcast."""
        try:
            rank, reason = self._control.last_error()
        except Exception:   # noqa: BLE001 — diagnostics must not kill the loop
            return
        if rank < 0 or not reason or reason.startswith("job aborted:"):
            return
        with self._lock:
            if (self._abort_status is None
                    and self._last_reported != (rank, reason)):
                self._pending_report = (rank, reason)
                self._last_reported = (rank, reason)

    def _handle_abort(self, rank: int, reason: str):
        """Latch a job-wide abort.  The coordinator broadcast the identical
        (rank, reason) payload to every process, so all ranks fail their
        outstanding and future eager work with the SAME attributed ABORTED
        status — no stranded waiters, no divergent error text."""
        status = Status.aborted(
            f"Horovod job aborted: rank {rank} failed: {reason}")
        with self._lock:
            if self._abort_status is None:
                self._abort_status = status
                _metrics.registry.inc("controller.aborts")
            else:
                status = self._abort_status
            self._shutdown.set()
        self._fail_all(status)

    def _handle_reconfigure(self, ext):
        """Adopt a membership change broadcast by the elastic coordinator.

        By the time Tick returned the RECONFIGURE frame, the native plane
        has already re-ranked the survivors, re-bootstrapped the data
        plane and flushed its response cache.  The Python side quiesces:
        every in-flight entry completes RETRYABLE (the elastic driver
        restores from the latest checkpoint and re-submits — these
        collectives negotiated against a world that no longer exists),
        local negotiation state is dropped, and the controller re-reads
        its identity from the native plane so ``hvd.rank()``/``size()``
        report the post-reconfigure world."""
        from horovod_tpu import cpp_core
        if ext.lost_rank >= 0:
            cause = (f"rank {ext.lost_rank} was lost "
                     f"({ext.lost_reason or 'no reason recorded'})")
        else:
            cause = ext.lost_reason or "membership changed"
        status = Status.retryable(
            f"Horovod membership reconfigured at generation "
            f"{ext.generation}: {cause}. Restore from the latest "
            "checkpoint and retry.")
        with self._lock:
            # Failure reports attributed under the OLD generation must not
            # ride the next tick — the coordinator already acted on them.
            self._pending_report = None
            self._last_reported = None
            self._stall_warned.clear()
        old_pidx = self.topology.process_index
        pidx, pcount, first_rank, generation = self._control.membership()
        lsize = self.topology.local_size
        new_size = pcount * lsize
        self.topology = dataclasses.replace(
            self.topology, process_index=pidx, process_count=pcount,
            rank_override=first_rank, size_override=new_size)
        self.size = new_size
        # Dense re-rank: uniform ranks-per-process is an elastic-mode
        # precondition (the native plane refuses elastic otherwise), so the
        # rank map is pure arithmetic — no layout re-exchange over a ring
        # whose peers are mid-training.
        self._rank_to_process.clear()
        for r in range(new_size):
            self._rank_to_process[r] = r // lsize
        ex = getattr(self, "_executor", None)
        if ex is not None:
            ex.topology = self.topology
            ex.nranks = new_size
        # The local message table is idle in distributed mode, but keep it
        # sized to the live world so readiness counts stay correct if it is
        # ever consulted.
        if self._use_cpp:
            self._message_table = cpp_core.CppMessageTable(
                new_size, self.timeline)
        else:
            self._message_table = MessageTable(new_size, self.timeline)
        self._message_table.configure_algo_selection(
            self.num_hosts, pcount, algo_crossover_bytes())
        # Fold into the framework-global snapshot so rank()/size() queries
        # report the new identity.
        from horovod_tpu import basics
        if basics._state.controller is self:
            basics._state.topology = self.topology
        # Per-set elastic rides the pod event: every registered set
        # containing the lost rank reconfigures itself (generation bump +
        # tagged-series retirement) — the other tenants are untouched.
        from horovod_tpu import process_set as _process_set_mod
        try:
            _process_set_mod.on_pod_reconfigure(ext.lost_rank)
        except Exception:   # noqa: BLE001 — tenant bookkeeping must not
            pass            # block pod survival
        _metrics.registry.set_gauge("membership.generation", generation)
        # Published LAST, after rank()/size() report the new world: the
        # seam elastic.generation() reads.  Training threads poll it to
        # detect a between-steps reconfigure; publishing the native value
        # early would let them observe the new generation while the
        # framework rank is still the old one and enqueue a request
        # stamped with an out-of-range rank into a new-generation frame.
        self._adopted_generation = generation
        # Quiesce LAST, once rank()/size() and the adopted generation all
        # describe the new world: _fail_all completes every in-flight
        # entry RETRYABLE (the elastic driver restores from the latest
        # checkpoint and re-submits), and the woken training threads
        # immediately rebuild their requests from the framework identity.
        # Waking them before the identity update would let a retry stamp
        # an out-of-range old-world rank into a new-generation frame.
        self._fail_all(status)
        cpp_core.flight_record(
            "elastic.adopted", f"gen={generation}", first_rank, new_size)
        if pidx == 0 and old_pidx != 0:
            # Coordinator failover seated THIS process as the successor
            # (docs/elasticity.md): the native plane already swapped its
            # worker tick loop for the coordinator role, and the stall
            # scanner above keys off process_index, so coordinator-side
            # duties start here automatically.  Note the promotion so an
            # operator can tell a takeover from a plain shrink.
            print(f"horovod_tpu elastic: this process (was process "
                  f"{old_pidx}) took over as coordinator", file=sys.stderr)
        print(f"horovod_tpu elastic: continuing at generation {generation} "
              f"as rank {first_rank} of {new_size} "
              f"(process {pidx} of {pcount})", file=sys.stderr)

    def _maybe_check_stalls_distributed(self):
        if self.stall_check_disabled or not self.topology.is_coordinator:
            return
        now = time.monotonic()
        if now - self._last_stall_check < self.stall_warning_time_s:
            return
        self._last_stall_check = now
        self._warn_stalled(self._control.stalled(self.stall_warning_time_s))

    def _maybe_inject_slow_fault(self):
        """Python-controller half of the ``slow`` fault: a deterministic
        per-tick delay in the local negotiation loop.  Multi-process
        ticks delegate to the native plane, which injects the same delay
        there (control.cc MaybeInjectFault) — never both, so the stall
        lands exactly once per tick."""
        self._fault_tick += 1
        for i, fs in enumerate(self._fault_specs):
            if fs.mode != "slow" or not 0 <= fs.rank < self.size:
                continue
            if fs.tick >= 0 and self._fault_tick < fs.tick:
                continue
            if i not in self._slow_announced:
                self._slow_announced.add(i)
                print(f"horovod_tpu fault injection: slowing rank "
                      f"{fs.rank} by {fs.ms}ms per tick from tick "
                      f"{self._fault_tick}", file=sys.stderr)
            time.sleep(fs.ms / 1e3)

    def _run_loop_once(self):
        if self._fault_specs:
            self._maybe_inject_slow_fault()
        with self._lock:
            pending = list(self._message_queue)
            self._message_queue.clear()

        # Non-default process sets negotiate on the SAME tick but in their
        # own namespaces: partition first, run each set's pass, and keep
        # the default path below byte-identical when only set 0 exists.
        if any(r.process_set for r in pending):
            set_pending: Dict[int, List[Request]] = {}
            default_pending: List[Request] = []
            for r in pending:
                if r.process_set:
                    set_pending.setdefault(r.process_set, []).append(r)
                else:
                    default_pending.append(r)
            pending = default_pending
            self._negotiate_sets(set_pending)

        # Response cache: a batch byte-identical to an earlier
        # fully-successful tick replays that tick's fused responses,
        # skipping the table and the fusion planner.  Only sound when the
        # table is empty on both sides of the original tick — a straggler
        # could otherwise have contributed to the stored responses.
        cache = self._local_cache
        t0 = time.monotonic()
        table_was_empty = bool(cache is not None and pending
                               and len(self._message_table) == 0)
        fused = None
        if cache is not None and pending:
            fused = cache.lookup(pending, table_empty=table_was_empty)
        cached_tick = fused is not None

        if not cached_tick:
            # Negotiation.  Single-process: this process speaks for every
            # rank, so readiness resolves locally.  Multi-process: local
            # requests are forwarded to the rank-0 coordinator over the
            # control plane (C++ core), which gathers/validates and
            # broadcasts responses.
            responses: List[Response] = []
            for req in pending:
                if self._message_table.increment(req):
                    responses.append(
                        self._message_table.construct_response(
                            req.tensor_name))

            if not responses:
                self._maybe_check_stalls()
                self._tick_telemetry()
                return

            def entry_bytes(name: str) -> int:
                e = self._tensor_table[name]
                return (int(np.prod(e.per_rank[0].shape))
                        * np.dtype(e.dtype).itemsize)

            def entry_dtype(name: str) -> str:
                return self._tensor_table[name].dtype

            fused = self._plan_fusion(responses, entry_bytes, entry_dtype,
                                      self.fusion_threshold)
            if (cache is not None and table_was_empty
                    and len(self._message_table) == 0
                    and all(r.response_type != ResponseType.ERROR
                            for r in fused)
                    and {n for r in fused for n in r.tensor_names}
                        == {req.tensor_name for req in pending}):
                cache.store(pending, fused)
            _metrics.registry.observe("control.tick_seconds#cached=0",
                                      time.monotonic() - t0)
        else:
            dur = time.monotonic() - t0
            _metrics.registry.observe("control.tick_seconds#cached=1", dur)
            tl = self.timeline
            if tl is not None and hasattr(tl, "cache_hit_tick"):
                tl.cache_hit_tick(int(dur * 1e6))

        ready = []
        for resp in fused:
            with self._lock:
                entries = [self._tensor_table.pop(n) for n in resp.tensor_names]
            ready.append((resp, entries))
        if self.timeline:
            # QUEUE span per negotiated tensor: response constructed →
            # executor start (reference operations.h:35, cc:951).
            for _, entries in ready:
                self.timeline.activity_start_all(entries, "QUEUE")
        self._execute_ready(ready)

        self._maybe_check_stalls()
        self._tick_telemetry()

    def _negotiate_sets(self, set_pending: Dict[int, List[Request]]):
        """Local negotiation for non-default process sets.

        Each set runs its own table pass and its OWN planner invocation —
        responses never fuse across sets (native parity: the coordinator
        appends set responses after PlanTick), and the default response
        cache never sees set traffic.  Per-tenant observability: request
        and tick-latency series tagged ``#process_set=<name>``."""
        for sid in sorted(set_pending):
            reqs = set_pending[sid]
            ps = self._process_sets.get(sid)
            tag = ps.name if ps is not None else str(sid)
            t0 = time.monotonic()
            responses: List[Response] = []
            for req in reqs:
                rc = self._process_sets.increment(sid, req)
                if rc < 0:
                    responses.append(Response(
                        response_type=ResponseType.ERROR,
                        tensor_names=[req.tensor_name],
                        error_message="Request rank out of range.",
                        process_set=sid))
                elif rc == 1:
                    responses.append(
                        self._process_sets.construct_response(
                            sid, req.tensor_name))
            _metrics.registry.inc(
                f"control.set_requests#process_set={tag}", len(reqs))
            if not responses:
                continue

            def entry_bytes(name: str) -> int:
                e = self._tensor_table[name]
                return (int(np.prod(e.per_rank[0].shape))
                        * np.dtype(e.dtype).itemsize)

            def entry_dtype(name: str) -> str:
                return self._tensor_table[name].dtype

            fused = self._plan_fusion(responses, entry_bytes, entry_dtype,
                                      self.fusion_threshold)
            # The planner predates sets; re-stamp so pop guards and the
            # execution branch route by the right namespace.
            for resp in fused:
                resp.process_set = sid
            ready = []
            for resp in fused:
                with self._lock:
                    entries = [self._tensor_table.pop(n)
                               for n in resp.tensor_names
                               if n in self._tensor_table
                               and self._tensor_table[n].process_set == sid]
                ready.append((resp, entries))
            if self.timeline:
                for _, entries in ready:
                    self.timeline.activity_start_all(entries, "QUEUE")
            self._execute_ready(ready)
            _metrics.registry.observe(
                f"control.tick_seconds#process_set={tag}",
                time.monotonic() - t0)

    def _execute_set(self, resp: Response, entries):
        """Set-scoped host data plane: a process-local set's collectives
        reduce/concat/broadcast the member contributions this process
        holds (enqueue enforced full membership) — the negotiated
        response only ordered and validated them, and a tenant's eager
        traffic never touches the pod-wide device mesh."""
        from horovod_tpu import process_set as _process_set_mod
        if resp.response_type == ResponseType.ERROR:
            status = Status(StatusType.PRECONDITION_ERROR,
                            resp.error_message)
            for e in entries:
                e.callback(status, None)
            return
        ps = self._process_sets.get(resp.process_set)
        for e in entries:
            size = ps.size() if ps is not None else len(e.per_rank)
            try:
                out = _process_set_mod.execute_host(e, size)
            except Exception as exc:   # noqa: BLE001 — propagate as status
                e.callback(Status(StatusType.UNKNOWN_ERROR, repr(exc)),
                           None)
            else:
                e.callback(Status.OK(), out)

    def _maybe_check_stalls(self):
        """Warn (once per minute) about tensors some ranks never submitted
        (reference ``CheckForStalledTensors``, ``operations.cc:1366-1412``)."""
        if self.stall_check_disabled:
            return
        now = time.monotonic()
        if now - self._last_stall_check < self.stall_warning_time_s:
            return
        self._last_stall_check = now
        self._warn_stalled(self._message_table.pending_names_older_than(
            self.stall_warning_time_s))

    def _warn_stalled(self, stalled):
        """``stalled`` is a list of (name, age_s, missing_ranks) records —
        the shape both the Python table and the native control plane
        report.  Identical warnings dedupe on the missing-rank set: a
        long-lived stall prints once, and re-warns only when the set of
        absent ranks changes; resolved tensors drop out so they may warn
        again on a later stall."""
        import sys
        _metrics.registry.set_gauge("controller.stalled_tensors",
                                    len(stalled))
        fresh = []
        current: Dict[str, frozenset] = {}
        for name, age, missing in stalled:
            key = frozenset(missing)
            current[name] = key
            if self._stall_warned.get(name) != key:
                fresh.append((name, age, missing))
        self._stall_warned = current
        if not fresh:
            return
        msg = ["WARNING: One or more tensors were submitted to be "
               "reduced, gathered or broadcasted by subset of ranks and "
               "are waiting for remainder of ranks for more than "
               f"{int(self.stall_warning_time_s)} seconds. This may "
               "indicate that different ranks are trying to submit "
               "different tensors or that only subset of ranks is "
               "submitting tensors, which will cause deadlock."]
        for name, age, missing in fresh:
            msg.append(f"Stalled op: {name} [waiting {age:.0f}s; "
                       f"missing ranks: {', '.join(map(str, missing))}]")
        print("\n".join(msg), file=sys.stderr)

    def _fail_all(self, status: Status):
        with self._lock:
            entries = list(self._tensor_table.values())
            self._tensor_table.clear()
            self._message_queue.clear()
            # Stale negotiation state would poison later reuse of the same
            # tensor names (the readiness count could overshoot `size`).
            self._message_table.clear()
        # Cached response sets are dead with the job — a restarted loop
        # must renegotiate from scratch (the native control plane flushes
        # its own cache in LatchAbort).
        if self._local_cache is not None:
            self._local_cache.flush()
        # Per-set negotiation state is scoped the same way: stale
        # set-local readiness counts would poison later reuse of the same
        # tensor names inside a tenant.
        self._process_sets.clear_negotiation_state()
        for e in entries:
            e.callback(status, None)
        # Keep the trace on disk usable while the job is failing: this
        # covers both the abort-broadcast path and tick-loop exceptions
        # (the atexit guard closes the JSON on process death).
        tl = self.timeline
        if tl is not None and hasattr(tl, "flush"):
            try:
                tl.flush()
            except Exception:   # noqa: BLE001 — best-effort on failure path
                pass

    def _tick_telemetry(self):
        """Per-tick observability: queue-depth / outstanding-handle gauges
        in the metrics registry plus Chrome-trace counter tracks (queue
        depth, bytes in flight) on the timeline.  Counter events are
        emitted only when the value changes so idle ticks cost nothing in
        the trace."""
        with self._lock:
            depth = len(self._tensor_table)
            in_flight = sum(int(c.nbytes)
                            for e in self._tensor_table.values()
                            for c in e.per_rank)
        _metrics.registry.set_gauge("controller.queue_depth", depth)
        _metrics.registry.set_gauge("controller.outstanding_handles",
                                    self.handle_manager.outstanding())
        tl = self.timeline
        if tl is not None and hasattr(tl, "counter"):
            for name, val in (("queue_depth", depth),
                              ("bytes_in_flight", in_flight)):
                if self._last_counters.get(name) != val:
                    self._last_counters[name] = val
                    tl.counter(name, val)

    def _close_timeline(self):
        """atexit / teardown hook: close the timeline into loadable JSON
        if it is still open.  Safe after stop() — close() is idempotent in
        both implementations, and a leaked native timeline (wedged
        shutdown) makes this a no-op."""
        tl = self.timeline
        if tl is None:
            return
        try:
            tl.close()
        except Exception:   # noqa: BLE001 — best-effort at interpreter exit
            pass
