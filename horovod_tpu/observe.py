"""Fleet performance observatory: the Python face of the per-hop
telemetry, step-time decomposition, and fleet aggregation that live in
``cpp/htpu/observe.{h,cc}`` and the coordinator's ``RunObservatory``.

``hvd.observe()`` returns one merged dict:

* ``"enabled"`` — whether the native observatory is armed
  (``HOROVOD_TPU_OBSERVE=1`` or ``observe.set_enabled(True)``);
* ``"local"`` — this process's native digest: step/compute/exposed/stall
  EWMAs, per-leg bandwidth EWMAs (classic/shm/uring/ctrl), step count,
  in-flight transfers;
* ``"fleet"`` — on the coordinator (process 0) only, the fleet view
  parsed back out of the ``fleet.*`` gauges the coordinator republishes
  every few ticks from the telemetry trailers it strips off tick
  frames: ``{"ranks": N, "by_rank": {rank: {...}}}``.

The step decomposition itself is fed from the training loop hooks
(``jax._overlapped_allreduce`` for the eager overlap path,
``spmd`` step wrappers for the in-jit path) through :func:`note_step`,
which routes to the native EWMAs when the core is loaded and always
mirrors into the Python registry so pure-Python runs still get
``step.*`` histograms in ``hvd.metrics()``.

Like :mod:`horovod_tpu.metrics`, this module is callable —
``hvd.observe()`` — because importing the submodule rebinds the package
attribute to the module object.
"""

from __future__ import annotations

import os
import sys
import types
from typing import Dict, Optional

from horovod_tpu import metrics as _metrics

#: Leg index order used by the native core (integrity.h ``enum Leg``).
LEGS = ("classic", "shm", "uring", "ctrl")

# Python-side fallback state for ``enabled()`` when the native core is
# absent: seeded from the env, flippable via set_enabled().
_py_enabled: Optional[bool] = None


def _env_enabled() -> bool:
    return os.environ.get("HOROVOD_TPU_OBSERVE", "").strip().lower() in (
        "1", "true", "yes", "on")


def enabled() -> bool:
    """Whether the observatory is armed (native state when available)."""
    global _py_enabled
    try:
        from horovod_tpu import cpp_core
        native = cpp_core.observe_enabled()
    except Exception:   # noqa: BLE001 — observability must never raise
        native = None
    if native is not None:
        return native
    if _py_enabled is None:
        _py_enabled = _env_enabled()
    return _py_enabled


def set_enabled(on: bool) -> None:
    """Flip the observatory at runtime (both native and Python state);
    used by the bench A/B and tests."""
    global _py_enabled
    _py_enabled = bool(on)
    try:
        from horovod_tpu import cpp_core
        cpp_core.observe_set_enabled(bool(on))
    except Exception:   # noqa: BLE001 — observability must never raise
        pass


def note_step(step_s: float, compute_s: float = 0.0, hidden_s: float = 0.0,
              exposed_s: float = 0.0, stall_s: float = 0.0) -> None:
    """Record one training step's wall-clock decomposition.

    Feeds the native EWMAs (which ride the telemetry trailer to the
    coordinator) when the core is loaded, and always mirrors into the
    Python registry's ``step.*`` histograms so ``hvd.metrics()`` and the
    JSONL exporter carry the series either way."""
    if not enabled():
        return
    try:
        from horovod_tpu import cpp_core
        cpp_core.observe_note_step(step_s, compute_s, hidden_s, exposed_s,
                                   stall_s)
    except Exception:   # noqa: BLE001 — observability must never raise
        pass
    reg = _metrics.registry
    reg.inc("step.count")
    reg.observe("step.seconds", step_s)
    reg.observe("step.compute_seconds", compute_s)
    reg.observe("step.hidden_comm_seconds", hidden_s)
    reg.observe("step.exposed_comm_seconds", exposed_s)
    reg.observe("step.stall_seconds", stall_s)


def local_snapshot() -> dict:
    """The native per-process digest; ``{}`` without the native core."""
    try:
        from horovod_tpu import cpp_core
        return cpp_core.observe_snapshot()
    except Exception:   # noqa: BLE001 — observability must never raise
        return {}


def fleet_from_gauges(gauges: Dict[str, float]) -> dict:
    """Reshape the coordinator's flat ``fleet.*#rank=R[,leg=L]`` gauges
    into ``{"ranks": N, "by_rank": {R: {...}}}``.  Pure so the tools
    (``fleet_top``, ``metrics_watch``) can reuse it on tailed JSONL."""
    by_rank: Dict[int, dict] = {}
    for name, value in gauges.items():
        if not name.startswith("fleet.") or "#" not in name:
            continue
        family, _, label_part = name.partition("#")
        labels = {}
        for kv in label_part.split(","):
            k, _, v = kv.partition("=")
            labels[k] = v
        try:
            rank = int(labels["rank"])
        except (KeyError, ValueError):
            continue
        row = by_rank.setdefault(rank, {})
        key = family[len("fleet."):]
        if key == "bandwidth_bps":
            row.setdefault("bandwidth_bps", {})[
                labels.get("leg", "?")] = value
        else:
            row[key] = value
    out = {"ranks": int(gauges.get("fleet.ranks", len(by_rank))),
           "by_rank": by_rank}
    return out


def snapshot() -> dict:
    """The merged observatory view returned by ``hvd.observe()``."""
    snap = _metrics.snapshot()
    return {
        "enabled": enabled(),
        "local": local_snapshot(),
        "fleet": fleet_from_gauges(snap.get("gauges", {})),
        "sentinel_alerts": {
            k.partition("=")[2]: v
            for k, v in snap.get("counters", {}).items()
            # Eagerly-registered kinds sit at zero until they fire; only
            # fired kinds belong in the user-facing alert map.
            if k.startswith("sentinel.alerts#kind=") and v
        },
    }


class _CallableModule(types.ModuleType):
    """Makes ``hvd.observe()`` a call and ``hvd.observe.note_step`` an
    attribute access — same idiom (and reason) as ``hvd.metrics``."""

    def __call__(self) -> dict:
        return snapshot()


sys.modules[__name__].__class__ = _CallableModule
