"""Parameter-publish serving plane: stream the training job's committed
checkpoint-chain tip to a subscriber process set.

The serving half of the multi-tenant design (docs/process-sets.md): a
training tenant checkpoints through the async incremental writer
(:mod:`horovod_tpu.ckpt_stream` → base+delta chains,
:mod:`horovod_tpu.checkpoint`), and a :class:`ParameterPublisher` watches
the chain directory for newly COMMITTED epochs — never a torn or
in-flight tip — and streams each one's reconstructed state to the
members of a publish process set via set-scoped broadcast.  Training
never stops: the publish traffic negotiates in the publish set's own
namespace on the shared coordinator tick and executes on the set-scoped
host data plane, so the training set's collectives and XLA programs are
untouched (the publish-while-training drill in ``bench.py`` measures
exactly this: publish latency + staleness vs the training step-time
delta).

Knobs:

* ``HOROVOD_TPU_PUBLISH_EVERY`` — publish every Nth committed epoch
  (default 1: every commit).
* ``HOROVOD_TPU_PUBLISH_TIMEOUT_S`` — per-publish broadcast timeout in
  seconds (default 60).

Metrics (docs/observability.md): ``publish.count``, ``publish.bytes``,
``publish.latency_seconds``, ``publish.staleness_seconds#process_set=``
and ``publish.epoch#process_set=`` / ``publish.latency_seconds#process_set=``
tagged with the publish set's name.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional

import numpy as np

from horovod_tpu import checkpoint as _checkpoint
from horovod_tpu import metrics as _metrics
from horovod_tpu import process_set as _process_set_mod


def publish_every_default() -> int:
    """HOROVOD_TPU_PUBLISH_EVERY: publish every Nth committed epoch
    (default 1 — every commit; malformed/non-positive falls back)."""
    raw = os.environ.get("HOROVOD_TPU_PUBLISH_EVERY", "")
    try:
        v = int(raw)
        return v if v >= 1 else 1
    except ValueError:
        return 1


def publish_timeout_default() -> float:
    """HOROVOD_TPU_PUBLISH_TIMEOUT_S: per-publish broadcast timeout
    (default 60 s; malformed/non-positive falls back)."""
    raw = os.environ.get("HOROVOD_TPU_PUBLISH_TIMEOUT_S", "")
    try:
        v = float(raw)
        return v if v > 0 else 60.0
    except ValueError:
        return 60.0


class ParameterPublisher:
    """Watch a checkpoint-chain directory and broadcast committed tips to
    a subscriber process set.

    ``process_set`` is the PUBLISH set (object, name, or id): its
    set-local ``root_rank`` (default 0) must be a rank holding the
    committed chain — typically the training tenant's first rank — and
    the remaining members are the subscribers.  :meth:`poll` is the
    cheap call for a serving loop: it publishes only when a new committed
    epoch (respecting ``HOROVOD_TPU_PUBLISH_EVERY``) has appeared, and
    returns the published state so a subscriber can swap weights in
    place.
    """

    def __init__(self, directory: str, process_set, *,
                 root_rank: int = 0,
                 every: Optional[int] = None,
                 timeout_s: Optional[float] = None):
        self.directory = directory
        self._ps = _process_set_mod.resolve(process_set)
        self._root = int(root_rank)
        if not 0 <= self._root < self._ps.size():
            raise ValueError(
                f"publish root rank {root_rank} is not a set-local rank "
                f"of process set '{self._ps.name}' "
                f"(size {self._ps.size()})")
        self.every = int(every) if every is not None else \
            publish_every_default()
        self.timeout_s = (float(timeout_s) if timeout_s is not None
                          else publish_timeout_default())
        # Last epoch actually streamed (-1 = nothing yet) and a
        # monotonically increasing publish sequence for tensor naming —
        # re-publishing the same epoch (subscriber set reconfigured) must
        # not collide with in-flight names.
        self.last_published_epoch = -1
        self._seq = 0

    # ------------------------------------------------------------- watching

    def committed_tip(self) -> int:
        """Highest committed (restorable) epoch in the directory, -1 when
        none.  Torn or in-flight chain tips are skipped — the publisher
        only ever streams state a recovery could also reach."""
        latest = _checkpoint.latest_epoch(self.directory)
        if latest < 0:
            return -1
        return _checkpoint.resolve_committed_epoch(self.directory,
                                                   latest)

    def pending_epoch(self) -> int:
        """The epoch :meth:`poll` would publish now, or -1: the committed
        tip, if it advanced at least ``every`` epochs past the last
        publish (first publish fires on any committed tip)."""
        tip = self.committed_tip()
        if tip < 0:
            return -1
        if self.last_published_epoch < 0:
            return tip
        if tip - self.last_published_epoch >= self.every:
            return tip
        return -1

    def poll(self) -> Optional[Dict[str, Any]]:
        """Publish the newest committed epoch if one is due; returns the
        published flat state, or None when nothing new is committed."""
        epoch = self.pending_epoch()
        if epoch < 0:
            return None
        return self.publish(epoch)

    # ----------------------------------------------------------- publishing

    def publish(self, epoch: Optional[int] = None) -> Dict[str, Any]:
        """Stream committed epoch ``epoch`` (default: the committed tip)
        to the publish set via set-scoped broadcast and return the flat
        state every member now holds.

        The chain is replayed on the ROOT member's process (committed
        links only — ``read_chain_state`` raises on a torn chain) and
        each leaf broadcasts in the publish set's namespace; key order is
        broadcast first so subscribers rebuild the exact dict."""
        from horovod_tpu.ops import eager as _eager
        if epoch is None:
            epoch = self.committed_tip()
        if epoch < 0:
            raise ValueError(
                f"no committed checkpoint in {self.directory!r} to "
                "publish")
        t0 = time.monotonic()
        flat = _checkpoint.read_chain_state(self.directory, epoch)
        # Staleness: how old the committed tip already was when this
        # publish started — commit-to-serve lag, the serving-plane SLO.
        commit_age = self._commit_age_s(epoch)
        self._seq += 1
        prefix = f"publish/{self._ps.name}/s{self._seq}"
        nbytes = 0
        out: Dict[str, Any] = {}
        for i, key in enumerate(sorted(flat)):
            leaf = np.asarray(flat[key])
            handle = _eager.broadcast_async(
                leaf, self._root, name=f"{prefix}/l{i}",
                process_set=self._ps)
            out[key] = _eager.synchronize(handle, timeout=self.timeout_s)
            nbytes += int(leaf.nbytes)
        latency = time.monotonic() - t0
        self.last_published_epoch = epoch
        tag = self._ps.name
        _metrics.registry.inc("publish.count")
        _metrics.registry.inc("publish.bytes", nbytes)
        _metrics.registry.observe("publish.latency_seconds", latency)
        _metrics.registry.observe(
            f"publish.latency_seconds#process_set={tag}", latency)
        if commit_age >= 0:
            _metrics.registry.observe(
                f"publish.staleness_seconds#process_set={tag}",
                commit_age + latency)
        _metrics.registry.set_gauge(
            f"publish.epoch#process_set={tag}", epoch)
        return out

    def _commit_age_s(self, epoch: int) -> float:
        """Seconds since the chain link for ``epoch`` was committed, from
        the manifest's mtime (-1 when unreadable — staleness is then
        unreported rather than wrong)."""
        path = os.path.join(
            _checkpoint.checkpoint_path(self.directory, epoch),
            _checkpoint.CHAIN_MANIFEST)
        try:
            return max(0.0, time.time() - os.path.getmtime(path))
        except OSError:
            return -1.0
