"""Elastic membership: survive rank loss by reconfiguring, not aborting.

With ``HOROVOD_TPU_ELASTIC=1`` the coordinator reacts to a confirmed-dead
rank by broadcasting RECONFIGURE instead of ABORT: survivors quiesce their
in-flight collectives (completed RETRYABLE, not ABORTED), ranks are
re-assigned densely (optionally admitting parked standbys launched with
``run.py --elastic --num-standby=N``), the data plane is re-bootstrapped,
and the job resumes under a bumped **membership generation** — every
control frame carries the generation, so stragglers from the old world are
rejected rather than corrupting the new one.

State machine (per process; see docs/elasticity.md for the full matrix)::

    RUN -> QUIESCE -> RERANK -> REBOOTSTRAP -> RESTORE -> RUN

The native plane (cpp/htpu/control.cc) owns QUIESCE/RERANK/REBOOTSTRAP;
this module owns RESTORE: :func:`run_elastic` re-enters the training
function from the latest checkpoint whenever a collective completes with
:class:`~horovod_tpu.ops.eager.HorovodRetryableError`.

Falls back to the classic abort path (PR 2 semantics, byte-identical
wire frames) when elastic mode is off or the surviving world would drop
below ``HOROVOD_TPU_ELASTIC_MIN_RANKS``.
"""

from __future__ import annotations

import os
import sys
from typing import Any, Callable, Optional, Sequence, Tuple

from horovod_tpu import basics


def enabled() -> bool:
    """True when this process runs in elastic mode
    (``HOROVOD_TPU_ELASTIC=1``)."""
    return os.environ.get("HOROVOD_TPU_ELASTIC", "") == "1"


def min_ranks() -> int:
    """Smallest world size a reconfiguration may shrink to
    (``HOROVOD_TPU_ELASTIC_MIN_RANKS``, default 1); below it the job
    aborts with the original attributed failure."""
    return int(os.environ.get("HOROVOD_TPU_ELASTIC_MIN_RANKS", "1"))


def is_standby() -> bool:
    """True when this process was launched as a parked standby
    (``HOROVOD_TPU_STANDBY=1``): it holds no rank until a
    reconfiguration admits it."""
    return os.environ.get("HOROVOD_TPU_STANDBY", "") == "1"


def generation() -> int:
    """Current membership generation: 0 until the first reconfiguration,
    bumped once per membership change; -1 before init or when no native
    control plane is attached (single-process jobs)."""
    if not basics.is_initialized():
        return -1
    controller = basics.controller()
    ctl = getattr(controller, "_control", None)
    if ctl is None:
        return -1
    # Read the PYTHON-ADOPTED generation (published by the controller
    # thread after it refreshed rank()/size()), not the native plane's:
    # the native value bumps inside the tick that applies the
    # reconfigure, a moment before the framework identity updates.  A
    # training thread polling the native value could observe the new
    # generation, retry, and build requests stamped with its OLD rank
    # into a new-generation frame — which the coordinator rejects as a
    # rank out of range.
    adopted = getattr(controller, "_adopted_generation", None)
    if adopted is not None:
        return adopted
    return ctl.membership()[3]


def successor_candidates(process_count: int) -> list:
    """Deterministic coordinator-successor order after process 0 is lost:
    the surviving process indices, ascending.  Every survivor walks the
    same list, so the first live candidate serves and the rest converge on
    it.  Mirrors the C++ election walk (``FailoverOnCoordLoss``) — the two
    are tested against each other."""
    return list(range(1, process_count))


def elect_successor(candidates: Sequence[int],
                    failed: Sequence[int] = ()) -> Optional[int]:
    """The elected successor: the lowest-indexed candidate not known to
    have failed (``failed`` = candidates that were unreachable or died
    mid-rendezvous, i.e. the cascade set).  None when every candidate is
    exhausted — the caller degrades to the classic attributed abort."""
    down = set(failed)
    for c in candidates:
        if c not in down:
            return c
    return None


def quorum_ok(survivors: int, ranks_per_process: int,
              min_ranks_floor: int) -> bool:
    """True when a successor may take over: the surviving world must stay
    at or above ``HOROVOD_TPU_ELASTIC_MIN_RANKS``.  Mirrors the C++ quorum
    gate (``FailoverServe``)."""
    return survivors * ranks_per_process >= min_ranks_floor


def init(ranks: Optional[Sequence[int]] = None) -> None:
    """``hvd.init()`` for elastic jobs.

    Identical to :func:`horovod_tpu.init` except for standbys: a standby
    whose admission wait expires without a seat (the job finished healthy
    and never needed it) exits 0 instead of raising — a spare that was
    never used is success, not failure.
    """
    try:
        basics.init(ranks)
    except Exception as exc:   # noqa: BLE001 — an unseated spare has no job
        if is_standby():
            print(f"horovod_tpu elastic: standby never admitted ({exc}); "
                  "exiting cleanly", file=sys.stderr)
            raise SystemExit(0)
        raise


# The active async snapshot stream, owned by run_elastic on the restore
# root (the writing rank).  Module-level so training loops can call
# elastic.snapshot(state, step) without threading the stream through.
_stream = None


def active_stream():
    """The run's :class:`~horovod_tpu.ckpt_stream.AsyncCheckpointer`
    (restore-root rank only, while inside :func:`run_elastic` with
    snapshotting on), else None."""
    return _stream


def snapshot(state: Any, step: int) -> bool:
    """Per-step hook for the async checkpoint stream: a cheap
    device→host snapshot every ``snapshot_every_steps`` steps on the
    writing rank; a no-op (False) everywhere else.  Re-raises the
    background writer's failure, if any, as the attributed
    ``HorovodRetryableError`` — on the owning rank, on the step path,
    where :func:`run_elastic` handles it."""
    s = _stream
    if s is None:
        return False
    return s.maybe_snapshot(state, step)


def run_elastic(train: Callable[[Any, int], Any], *, directory: str,
                like: Any, root_rank: int = 0,
                optional_keys: Tuple[str, ...] = (),
                max_reconfigures: int = 32,
                snapshot_every_steps: Optional[int] = None) -> Any:
    """Drive a training function across membership changes.

    ``train(state, resume_epoch)`` is entered with ``state`` restored
    from the latest checkpoint in ``directory`` (``like`` is the pytree
    template; ``resume_epoch`` is -1 on a fresh start) and re-entered —
    freshly restored — every time it raises
    :class:`~horovod_tpu.ops.eager.HorovodRetryableError`, i.e. every
    time the membership reconfigured under it.  ``train`` should
    checkpoint periodically; work since the last checkpoint is replayed
    after a reconfiguration.

    ``snapshot_every_steps`` (default: ``HOROVOD_TPU_CKPT_EVERY_STEPS``,
    0 = off) arms the async incremental stream (ckpt_stream.py): the
    root rank gets an :class:`~horovod_tpu.ckpt_stream.AsyncCheckpointer`
    seeded with the restored state, and ``train`` calls
    :func:`elastic.snapshot(state, step) <snapshot>` once per step —
    recovery then replays at most a snapshot interval plus the
    in-flight write instead of a full checkpoint interval.

    Returns ``train``'s return value (the stream is flushed first, so a
    clean exit leaves the final snapshot committed).  Aborts
    (:class:`~horovod_tpu.ops.eager.HorovodAbortedError`) and every
    other exception propagate unchanged — only membership changes retry.
    """
    import time

    from horovod_tpu import checkpoint, ckpt_stream
    from horovod_tpu import metrics as _metrics
    from horovod_tpu.ops.eager import HorovodRetryableError

    global _stream
    cadence = (snapshot_every_steps if snapshot_every_steps is not None
               else ckpt_stream.snapshot_every_steps_default())
    use_stream = cadence > 0 or ckpt_stream.async_enabled()
    attempts = 0
    while True:
        # The restore itself runs collectives (epoch agreement + parameter
        # broadcast), so a membership change landing mid-restore retries
        # the same way one landing mid-train does.
        try:
            t0 = time.monotonic()
            state, epoch = checkpoint.restore_and_broadcast(
                directory, like, root_rank=root_rank,
                optional_keys=optional_keys)
            if attempts:
                # Restore leg of a reconfiguration (native
                # elastic.downtime_seconds covers quiesce->rebootstrap;
                # this covers the Python restore+broadcast on top).
                _metrics.registry.observe("elastic.resume_seconds",
                                          time.monotonic() - t0)
                _metrics.registry.set_gauge("elastic.last_resume_s",
                                            time.monotonic() - t0)
            if use_stream and basics.rank() == root_rank:
                _stream = ckpt_stream.AsyncCheckpointer(
                    directory, snapshot_every_steps=cadence)
                _stream.seed(state, epoch)
            try:
                result = train(state, epoch)
                if _stream is not None:
                    # Surface a pending writer failure before declaring
                    # success; on a clean exit the final snapshot commits.
                    _stream.flush()
                return result
            finally:
                if _stream is not None:
                    _stream.close(flush=False)
                    _stream = None
        except HorovodRetryableError as exc:
            attempts += 1
            if attempts > max_reconfigures:
                raise
            print(f"horovod_tpu elastic: membership changed (generation "
                  f"{generation()}): {exc}; restoring from "
                  f"{directory!r} and re-entering train "
                  f"(reconfiguration {attempts})", file=sys.stderr)
