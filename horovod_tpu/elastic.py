"""Elastic membership: survive rank loss by reconfiguring, not aborting.

With ``HOROVOD_TPU_ELASTIC=1`` the coordinator reacts to a confirmed-dead
rank by broadcasting RECONFIGURE instead of ABORT: survivors quiesce their
in-flight collectives (completed RETRYABLE, not ABORTED), ranks are
re-assigned densely (optionally admitting parked standbys launched with
``run.py --elastic --num-standby=N``), the data plane is re-bootstrapped,
and the job resumes under a bumped **membership generation** — every
control frame carries the generation, so stragglers from the old world are
rejected rather than corrupting the new one.

State machine (per process; see docs/elasticity.md for the full matrix)::

    RUN -> QUIESCE -> RERANK -> REBOOTSTRAP -> RESTORE -> RUN

The native plane (cpp/htpu/control.cc) owns QUIESCE/RERANK/REBOOTSTRAP;
this module owns RESTORE: :func:`run_elastic` re-enters the training
function from the latest checkpoint whenever a collective completes with
:class:`~horovod_tpu.ops.eager.HorovodRetryableError`.

Falls back to the classic abort path (PR 2 semantics, byte-identical
wire frames) when elastic mode is off or the surviving world would drop
below ``HOROVOD_TPU_ELASTIC_MIN_RANKS``.
"""

from __future__ import annotations

import os
import sys
from typing import Any, Callable, Optional, Sequence, Tuple

from horovod_tpu import basics


def enabled() -> bool:
    """True when this process runs in elastic mode
    (``HOROVOD_TPU_ELASTIC=1``)."""
    return os.environ.get("HOROVOD_TPU_ELASTIC", "") == "1"


def min_ranks() -> int:
    """Smallest world size a reconfiguration may shrink to
    (``HOROVOD_TPU_ELASTIC_MIN_RANKS``, default 1); below it the job
    aborts with the original attributed failure."""
    return int(os.environ.get("HOROVOD_TPU_ELASTIC_MIN_RANKS", "1"))


def is_standby() -> bool:
    """True when this process was launched as a parked standby
    (``HOROVOD_TPU_STANDBY=1``): it holds no rank until a
    reconfiguration admits it."""
    return os.environ.get("HOROVOD_TPU_STANDBY", "") == "1"


def generation() -> int:
    """Current membership generation: 0 until the first reconfiguration,
    bumped once per membership change; -1 before init or when no native
    control plane is attached (single-process jobs)."""
    if not basics.is_initialized():
        return -1
    ctl = getattr(basics.controller(), "_control", None)
    if ctl is None:
        return -1
    return ctl.membership()[3]


def successor_candidates(process_count: int) -> list:
    """Deterministic coordinator-successor order after process 0 is lost:
    the surviving process indices, ascending.  Every survivor walks the
    same list, so the first live candidate serves and the rest converge on
    it.  Mirrors the C++ election walk (``FailoverOnCoordLoss``) — the two
    are tested against each other."""
    return list(range(1, process_count))


def elect_successor(candidates: Sequence[int],
                    failed: Sequence[int] = ()) -> Optional[int]:
    """The elected successor: the lowest-indexed candidate not known to
    have failed (``failed`` = candidates that were unreachable or died
    mid-rendezvous, i.e. the cascade set).  None when every candidate is
    exhausted — the caller degrades to the classic attributed abort."""
    down = set(failed)
    for c in candidates:
        if c not in down:
            return c
    return None


def quorum_ok(survivors: int, ranks_per_process: int,
              min_ranks_floor: int) -> bool:
    """True when a successor may take over: the surviving world must stay
    at or above ``HOROVOD_TPU_ELASTIC_MIN_RANKS``.  Mirrors the C++ quorum
    gate (``FailoverServe``)."""
    return survivors * ranks_per_process >= min_ranks_floor


def init(ranks: Optional[Sequence[int]] = None) -> None:
    """``hvd.init()`` for elastic jobs.

    Identical to :func:`horovod_tpu.init` except for standbys: a standby
    whose admission wait expires without a seat (the job finished healthy
    and never needed it) exits 0 instead of raising — a spare that was
    never used is success, not failure.
    """
    try:
        basics.init(ranks)
    except Exception as exc:   # noqa: BLE001 — an unseated spare has no job
        if is_standby():
            print(f"horovod_tpu elastic: standby never admitted ({exc}); "
                  "exiting cleanly", file=sys.stderr)
            raise SystemExit(0)
        raise


def run_elastic(train: Callable[[Any, int], Any], *, directory: str,
                like: Any, root_rank: int = 0,
                optional_keys: Tuple[str, ...] = (),
                max_reconfigures: int = 32) -> Any:
    """Drive a training function across membership changes.

    ``train(state, resume_epoch)`` is entered with ``state`` restored
    from the latest checkpoint in ``directory`` (``like`` is the pytree
    template; ``resume_epoch`` is -1 on a fresh start) and re-entered —
    freshly restored — every time it raises
    :class:`~horovod_tpu.ops.eager.HorovodRetryableError`, i.e. every
    time the membership reconfigured under it.  ``train`` should
    checkpoint periodically with :func:`horovod_tpu.checkpoint.save`;
    work since the last checkpoint is replayed after a reconfiguration.

    Returns ``train``'s return value.  Aborts
    (:class:`~horovod_tpu.ops.eager.HorovodAbortedError`) and every other
    exception propagate unchanged — only membership changes retry.
    """
    from horovod_tpu import checkpoint
    from horovod_tpu.ops.eager import HorovodRetryableError

    attempts = 0
    while True:
        # The restore itself runs collectives (epoch agreement + parameter
        # broadcast), so a membership change landing mid-restore retries
        # the same way one landing mid-train does.
        try:
            state, epoch = checkpoint.restore_and_broadcast(
                directory, like, root_rank=root_rank,
                optional_keys=optional_keys)
            return train(state, epoch)
        except HorovodRetryableError as exc:
            attempts += 1
            if attempts > max_reconfigures:
                raise
            print(f"horovod_tpu elastic: membership changed (generation "
                  f"{generation()}): {exc}; restoring from "
                  f"{directory!r} and re-entering train "
                  f"(reconfiguration {attempts})", file=sys.stderr)
