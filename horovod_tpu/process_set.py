"""Multi-tenant process sets: named communicators with their own
negotiation namespace.

Horovod's process-set API (``horovod/common/process_set.{h,cc}``,
``horovod/torch/mpi_ops.py:add_process_set``) lets training, eval and
auxiliary jobs share one pod without stepping on each other's
collectives.  This module is the Python half of the subsystem:

* :class:`ProcessSet` — one named communicator over a subset of global
  ranks, with per-set membership generation (per-set elastic: losing a
  rank reconfigures that set, never the pod).
* :class:`ProcessSetRegistry` — the behaviour-identical Python mirror of
  the native registry (``cpp/htpu/process_set.{h,cc}``, reachable via
  :class:`horovod_tpu.cpp_core.CppProcessSetTable`): each set owns a
  MessageTable sized to the set and indexed by SET-LOCAL rank, plus its
  own response-cache slots, so two disjoint sets negotiate concurrently
  with zero cross-talk.
* Module-level API (re-exported from ``horovod_tpu``):
  :func:`add_process_set`, :func:`remove_process_set`,
  :func:`process_set_by_name`, plus the ``HOROVOD_TPU_PROCESS_SETS``
  startup spec (``name:0,1;name2:2,3`` — same grammar the native
  coordinator parses in ``control.cc Create``).

Set ids start at 1 and are assigned in registration order; id 0 is the
implicit default/world set owned by the controller itself.  Multi-process
jobs must register sets through ``HOROVOD_TPU_PROCESS_SETS`` (every
process and the native coordinator parse the same spec, so ids agree by
construction); :func:`add_process_set` after init is single-process only
— the native coordinator's registry is sealed at Create and a dynamically
added id would be unknown to it.

The eager data plane for a non-default set is process-local: every member
rank of a set must be controlled by one process (the negotiated response
orders and validates the collective; execution reduces the member
contributions on host — see :func:`execute_host`).  See
docs/process-sets.md.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from horovod_tpu import metrics as _metrics

# Metric series retired when a set reconfigures or is removed (tag value =
# set name).  Keep in sync with docs/observability.md; counters survive by
# registry policy (remove_matching drops gauges/histograms only).
PER_SET_SERIES = (
    "control.negotiate_seconds",
    "control.tick_seconds",
    "control.set_requests",
    "elastic.set_generation",
    "publish.latency_seconds",
    "publish.staleness_seconds",
    "publish.epoch",
)


class ProcessSet:
    """One named communicator over a subset of global ranks.

    Mirrors the native ``htpu::ProcessSet`` (cpp/htpu/process_set.h):
    ascending member ranks, a set-local rank space, and a membership
    generation bumped by per-set reconfiguration."""

    def __init__(self, set_id: int, name: str, ranks: Sequence[int]):
        self.id = int(set_id)
        self.name = name
        self.ranks: Tuple[int, ...] = tuple(sorted(int(r) for r in ranks))
        self.generation = 0

    def size(self) -> int:
        return len(self.ranks)

    def included(self, global_rank: int) -> bool:
        return int(global_rank) in self.ranks

    def local_rank(self, global_rank: int) -> int:
        """SET-LOCAL rank of ``global_rank`` (-1 when not a member)."""
        try:
            return self.ranks.index(int(global_rank))
        except ValueError:
            return -1

    def rank(self) -> int:
        """Set-local rank of this process's first controlled global rank
        (-1 when this process controls no member) — the per-set analogue
        of ``hvd.rank()``."""
        from horovod_tpu import basics
        return self.local_rank(basics._require_init().topology.rank)

    def __repr__(self) -> str:
        return (f"ProcessSet(id={self.id}, name={self.name!r}, "
                f"ranks={list(self.ranks)}, generation={self.generation})")


def parse_spec(spec: str) -> List[Tuple[str, List[int]]]:
    """Parse the ``HOROVOD_TPU_PROCESS_SETS`` grammar
    (``name:0,1;name2:2,3``) into ``[(name, ranks), ...]``; raises
    ``ValueError`` on a malformed spec — same strictness as the native
    parser (``ProcessSetTable::ParseSpec``), which refuses init rather
    than silently dropping a tenant."""
    out: List[Tuple[str, List[int]]] = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        name, sep, ranks_txt = part.partition(":")
        name = name.strip()
        if not sep or not name:
            raise ValueError(
                f"malformed process-set spec entry {part!r}: expected "
                "'name:rank,rank,...' entries separated by ';'")
        try:
            ranks = [int(tok) for tok in ranks_txt.split(",") if tok.strip()]
        except ValueError:
            raise ValueError(
                f"malformed process-set spec entry {part!r}: ranks must "
                "be integers") from None
        if not ranks or any(r < 0 for r in ranks):
            raise ValueError(
                f"malformed process-set spec entry {part!r}: needs at "
                "least one non-negative rank")
        out.append((name, ranks))
    return out


class ProcessSetRegistry:
    """Python mirror of the native ``ProcessSetTable``: registered sets
    plus their scoped negotiation state (MessageTable + response cache per
    set).  Mutex-guarded so the controller's tick thread can negotiate on
    one set while a framework thread registers or tears down another."""

    def __init__(self, cache_capacity: int = 0):
        self._lock = threading.Lock()
        self._cache_capacity = int(cache_capacity)
        self._next_id = 1
        self._sets: Dict[int, ProcessSet] = {}
        self._tables: Dict[int, object] = {}
        self._caches: Dict[int, object] = {}

    # --------------------------------------------------------- registration

    def parse_spec(self, spec: str) -> bool:
        """Register every set in ``spec``; False (earlier entries stay
        registered — native parity) on a malformed spec or a rejected
        registration."""
        try:
            entries = parse_spec(spec)
        except ValueError:
            return False
        for name, ranks in entries:
            if self.add(name, ranks) < 0:
                return False
        return True

    def add(self, name: str, ranks: Sequence[int]) -> int:
        """Register a set; returns the new id, or -1 on invalid input
        (empty membership, duplicate rank, duplicate name)."""
        members = sorted(int(r) for r in ranks)
        with self._lock:
            if (not name or not members
                    or len(set(members)) != len(members)
                    or any(ps.name == name for ps in self._sets.values())):
                return -1
            sid = self._next_id
            self._next_id += 1
            ps = ProcessSet(sid, name, members)
            self._sets[sid] = ps
            self._tables[sid] = self._new_table(len(members))
            self._caches[sid] = self._new_cache(len(members))
            return sid

    @staticmethod
    def _new_table(size: int):
        from horovod_tpu.core import MessageTable
        return MessageTable(size)

    def _new_cache(self, size: int):
        del size   # capacity-bounded like the native per-set cache slots
        from horovod_tpu.core import _LocalResponseCache
        return _LocalResponseCache(self._cache_capacity)

    def remove(self, set_id: int) -> bool:
        """Tear a set down; True if it existed.  In-flight requests for
        the removed set error out at routing, never cross-talk."""
        with self._lock:
            if set_id not in self._sets:
                return False
            ps = self._sets.pop(set_id)
            self._tables.pop(set_id, None)
            self._caches.pop(set_id, None)
        retire_metrics(ps.name)
        return True

    # -------------------------------------------------------------- queries

    def get(self, set_id: int) -> Optional[ProcessSet]:
        with self._lock:
            return self._sets.get(int(set_id))

    def by_name(self, name: str) -> Optional[ProcessSet]:
        with self._lock:
            for ps in self._sets.values():
                if ps.name == name:
                    return ps
        return None

    def id_of(self, name: str) -> int:
        ps = self.by_name(name)
        return ps.id if ps is not None else -1

    def count(self) -> int:
        with self._lock:
            return len(self._sets)

    def size_of(self, set_id: int) -> int:
        ps = self.get(set_id)
        return ps.size() if ps is not None else -1

    def local_rank(self, set_id: int, global_rank: int) -> int:
        ps = self.get(set_id)
        return ps.local_rank(global_rank) if ps is not None else -1

    def generation(self, set_id: int) -> int:
        ps = self.get(set_id)
        return ps.generation if ps is not None else -1

    def all(self) -> List[ProcessSet]:
        with self._lock:
            return list(self._sets.values())

    # -------------------------------------------------------------- elastic

    def reconfigure(self, set_id: int, lost_global_rank: int) -> int:
        """Per-set elastic reconfiguration: drop the lost rank from the
        set's membership, clear its negotiation state (stale set-local
        ranks would corrupt later negotiations), bump the generation.
        Returns the new generation, or -1 on an unknown set/rank."""
        with self._lock:
            ps = self._sets.get(int(set_id))
            if ps is None or not ps.included(lost_global_rank):
                return -1
            ps.ranks = tuple(r for r in ps.ranks
                             if r != int(lost_global_rank))
            ps.generation += 1
            self._tables[set_id] = self._new_table(len(ps.ranks))
            self._caches[set_id] = self._new_cache(len(ps.ranks))
            gen = ps.generation
            name = ps.name
        retire_metrics(name)
        _metrics.registry.set_gauge(
            f"elastic.set_generation#process_set={name}", gen)
        return gen

    # ---------------------------------------------------------- negotiation

    def increment(self, set_id: int, request) -> int:
        """Route one request into its set's table: 1 when the tensor is
        ready to construct, 0 when still waiting, -1 on an unknown set or
        a set-local rank out of range (native ``Increment`` parity)."""
        with self._lock:
            ps = self._sets.get(int(set_id))
            table = self._tables.get(int(set_id))
        if ps is None or table is None:
            return -1
        if not 0 <= request.request_rank < ps.size():
            return -1
        return 1 if table.increment(request) else 0

    def construct_response(self, set_id: int, name: str):
        """Construct the set's response for ``name`` (after
        :meth:`increment` returned 1); the response's ``process_set`` is
        stamped.  Raises ``KeyError`` on an unknown set."""
        with self._lock:
            table = self._tables.get(int(set_id))
        if table is None:
            raise KeyError(f"unknown process set id {set_id}")
        resp = table.construct_response(name)
        resp.process_set = int(set_id)
        return resp

    def clear_negotiation_state(self) -> None:
        """Abort/quiesce: drop every set's readiness counts and cached
        responses (membership and generations survive — only in-flight
        negotiation dies with the job)."""
        with self._lock:
            tables = list(self._tables.values())
            caches = list(self._caches.values())
        for t in tables:
            t.clear()
        for c in caches:
            c.flush()


# --------------------------------------------------------------------------
# Module-global registry + public API
# --------------------------------------------------------------------------

_registry: Optional[ProcessSetRegistry] = None
_registry_lock = threading.Lock()


def registry() -> ProcessSetRegistry:
    """The process-global set registry (created on first use; seeded from
    ``HOROVOD_TPU_PROCESS_SETS`` so the Python ids match the native
    coordinator's, which parses the same spec at Create)."""
    global _registry
    with _registry_lock:
        if _registry is None:
            from horovod_tpu.core import cache_capacity_from_env
            reg = ProcessSetRegistry(cache_capacity_from_env())
            spec = os.environ.get("HOROVOD_TPU_PROCESS_SETS", "")
            if spec:
                # Loud failure: a silently dropped tenant would deadlock
                # its first collective 60s later.  parse_spec() raised
                # semantics live in the helper; registration rejects
                # (dup name/rank) surface here.
                entries = parse_spec(spec)
                for name, ranks in entries:
                    if reg.add(name, ranks) < 0:
                        raise ValueError(
                            f"HOROVOD_TPU_PROCESS_SETS rejected entry "
                            f"{name!r} (duplicate name or rank in "
                            f"{ranks})")
            _registry = reg
        return _registry


def reset() -> None:
    """Drop the global registry (tests + shutdown); the next access
    re-seeds from the environment."""
    global _registry
    with _registry_lock:
        _registry = None


def get(set_id: int) -> Optional[ProcessSet]:
    return registry().get(set_id)


def resolve(process_set) -> ProcessSet:
    """Accept a :class:`ProcessSet`, a set name, or a numeric id; raises
    ``ValueError`` on anything unknown."""
    reg = registry()
    if isinstance(process_set, ProcessSet):
        ps = reg.get(process_set.id)
        if ps is not None:
            return ps
    elif isinstance(process_set, str):
        ps = reg.by_name(process_set)
        if ps is not None:
            return ps
    elif isinstance(process_set, int) and process_set != 0:
        ps = reg.get(process_set)
        if ps is not None:
            return ps
    raise ValueError(
        f"Unknown process set {process_set!r}: register it with "
        "hvd.add_process_set([...], name=...) or the "
        "HOROVOD_TPU_PROCESS_SETS spec (see docs/process-sets.md).")


def add_process_set(ranks: Sequence[int],
                    name: Optional[str] = None) -> ProcessSet:
    """Register a named process set over ``ranks`` (reference
    ``hvd.add_process_set``).  Multi-process jobs must use the
    ``HOROVOD_TPU_PROCESS_SETS`` startup spec instead — the native
    coordinator's registry is sealed at init, so a dynamically added id
    would be unknown to it and every collective on it would error."""
    from horovod_tpu import basics
    st = basics._state
    if (st.initialized and st.topology is not None
            and st.topology.process_count > 1):
        raise RuntimeError(
            "add_process_set() after init is single-process only: "
            "multi-process jobs register sets with "
            "HOROVOD_TPU_PROCESS_SETS=<name:ranks;...> on every process "
            "so the coordinator knows them too (docs/process-sets.md).")
    reg = registry()
    if name is None:
        name = "set_" + ",".join(str(int(r)) for r in sorted(ranks))
    sid = reg.add(name, ranks)
    if sid < 0:
        raise ValueError(
            f"add_process_set rejected {name!r} over {list(ranks)}: "
            "empty membership, duplicate rank, or duplicate name.")
    _metrics.registry.set_gauge(
        f"elastic.set_generation#process_set={name}", 0)
    return reg.get(sid)


def remove_process_set(process_set) -> bool:
    """Tear a set down (by object, name, or id); True if it existed."""
    try:
        ps = resolve(process_set)
    except ValueError:
        return False
    return registry().remove(ps.id)


def process_set_by_name(name: str) -> Optional[ProcessSet]:
    return registry().by_name(name)


def reconfigure_process_set(process_set, lost_global_rank: int) -> int:
    """Per-set elastic: drop ``lost_global_rank`` from the set, retire its
    tagged metric series, bump and return the new generation (-1 on an
    unknown set/rank).  The pod is untouched — this is the per-tenant
    failure domain (docs/process-sets.md)."""
    ps = resolve(process_set)
    return registry().reconfigure(ps.id, lost_global_rank)


def on_pod_reconfigure(lost_global_rank: int) -> None:
    """Pod-level membership-change hook (elastic RECONFIGURE broadcast):
    every registered set containing the lost rank reconfigures itself —
    its generation advances independently of the pod's."""
    if lost_global_rank < 0 or _registry is None:
        return
    reg = registry()
    for ps in reg.all():
        if ps.included(lost_global_rank):
            reg.reconfigure(ps.id, lost_global_rank)


def retire_metrics(set_name: str) -> None:
    """Retire every per-set gauge/histogram series tagged with this set
    (membership changed or set removed: the old series describe a world
    that no longer exists; counters survive as process-lifetime totals,
    same policy as the pod re-rank path)."""
    for prefix in PER_SET_SERIES:
        _metrics.registry.remove_matching(
            f"{prefix}#process_set={set_name}")


# --------------------------------------------------------------------------
# Set-scoped host data plane
# --------------------------------------------------------------------------

def execute_host(entry, set_size: int):
    """Execute one negotiated set-scoped collective on host.

    ``entry.per_rank`` holds one contribution per member rank in
    set-local order (enqueue enforced process-local full membership), so
    the collective is pure numpy: sum (÷ size for average) for
    allreduce, dim0-concat in set-local rank order for allgather, the
    set-local root's value for broadcast.  Results are host ``ndarray``s
    — the set plane never touches the pod-wide device mesh, so a
    tenant's eager traffic cannot perturb the training job's XLA
    programs."""
    from horovod_tpu.core import RequestType
    contribs = [np.asarray(a) for a in entry.per_rank]
    if entry.request_type == RequestType.ALLREDUCE:
        out = np.sum(np.stack(contribs), axis=0,
                     dtype=np.dtype(entry.dtype))
        if entry.average:
            if np.issubdtype(np.dtype(entry.dtype), np.floating):
                out = (out / set_size).astype(entry.dtype)
            else:
                out = out // set_size
        return out
    if entry.request_type == RequestType.ALLGATHER:
        return np.concatenate(contribs, axis=0)
    if entry.request_type == RequestType.BROADCAST:
        if not 0 <= entry.root_rank < len(contribs):
            raise ValueError(
                f"set-local root rank {entry.root_rank} out of range "
                f"for a {len(contribs)}-member process set")
        return contribs[entry.root_rank].copy()
    raise ValueError(f"bad request type {entry.request_type}")
