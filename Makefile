# Top-level convenience targets.  The native core itself builds via
# cpp/Makefile (invoked automatically by horovod_tpu/cpp_core.py on
# first import); this file bundles the repo-wide hygiene gates.

PYTHON ?= python

# Everything a reviewer runs before trusting a change, minus the slow
# multi-process tests: the cross-language contract checkers (knob parity,
# C API/ctypes signatures, metric names, signal safety) plus both
# sanitizer smoke binaries built AND executed.  Fails on any finding,
# any sanitizer report, or any build warning-turned-error.
check: analyze asan tsan
	ASAN_OPTIONS=detect_leaks=0 ./cpp/htpu_smoke_asan
	TSAN_OPTIONS=halt_on_error=1 ./cpp/htpu_smoke_tsan

# The static-analysis suite alone (fast, no toolchain needed).
# See docs/static-analysis.md for what each checker enforces.
analyze:
	$(PYTHON) -m tools.analyze

asan:
	$(MAKE) -C cpp asan

tsan:
	$(MAKE) -C cpp tsan

# Tier-1 test suite, same invocation ROADMAP.md documents.
test:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/ -q -m 'not slow'

clean:
	$(MAKE) -C cpp clean
	rm -rf horovod_tpu/lib
	find . -name __pycache__ -type d -prune -exec rm -rf {} +

.PHONY: check analyze asan tsan test clean
