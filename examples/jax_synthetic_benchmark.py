"""Synthetic benchmark — TPU-native counterpart of the reference's
``examples/pytorch_synthetic_benchmark.py``: synthetic images, full training
step, img/sec mean ± 1.96σ per device and aggregate (reference ``:93-110``).

Fusion on/off comparison (BASELINE.json config 4): pass
``--no-fusion`` to disable trace-time gradient fusion — gradients are then
allreduced one XLA collective per tensor instead of letting XLA bucket them,
mirroring ``HOROVOD_FUSION_THRESHOLD=0``.
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.jax.spmd import make_train_step, shard_batch
from horovod_tpu.models import ResNet50, ResNet101, ResNet152


MODELS = {"resnet50": ResNet50, "resnet101": ResNet101,
          "resnet152": ResNet152}


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50", choices=sorted(MODELS))
    p.add_argument("--batch-size", type=int, default=32,
                   help="per-rank batch size (reference default 32)")
    p.add_argument("--num-warmup-batches", type=int, default=10)
    p.add_argument("--num-batches-per-iter", type=int, default=10)
    p.add_argument("--num-iters", type=int, default=10)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--no-fusion", action="store_true",
                   help="one collective per gradient tensor (fusion off)")
    args = p.parse_args()

    hvd.init()
    mesh = hvd.ranks_mesh()
    n = hvd.size()
    batch = args.batch_size * n

    model = MODELS[args.model](num_classes=1000, dtype=jnp.bfloat16)
    rng = jax.random.PRNGKey(0)
    images = jax.random.normal(
        rng, (batch, args.image_size, args.image_size, 3), jnp.bfloat16)
    labels = jnp.zeros((batch,), jnp.int32)
    variables = model.init(rng, images[:1], train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    def loss_fn(params, batch_stats, data):
        imgs, lbls = data
        logits, mut = model.apply(
            {"params": params, "batch_stats": batch_stats}, imgs,
            train=True, mutable=["batch_stats"])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, lbls).mean()
        return loss, mut["batch_stats"]

    tx = optax.sgd(0.01, momentum=0.9)
    opt_state = tx.init(params)

    if args.no_fusion:
        # Per-tensor collectives: an optimization barrier between gradient
        # allreduces stops XLA from bucketing them (the runtime analogue of
        # HOROVOD_FUSION_THRESHOLD=0).
        from jax import shard_map

        def step_body(params, batch_stats, opt_state, data):
            # Varying view of the params so the cotangents are raw
            # per-shard gradients (see make_train_step); the explicit
            # per-tensor pmean below is then the mean, not a double-sum.
            from horovod_tpu.parallel._vma import ensure_varying_tree
            params_v = ensure_varying_tree(params, ("ranks",))
            (loss, new_bs), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params_v, batch_stats, data)
            leaves, treedef = jax.tree.flatten(grads)
            reduced = []
            for leaf in leaves:
                leaf = lax.pmean(leaf, "ranks")
                leaf = lax.optimization_barrier(leaf)
                reduced.append(leaf)
            grads = jax.tree.unflatten(treedef, reduced)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            new_bs = jax.tree.map(lambda a: lax.pmean(a, "ranks"), new_bs)
            return params, new_bs, opt_state, lax.pmean(loss, "ranks")

        step = jax.jit(shard_map(
            step_body, mesh=mesh,
            in_specs=(P(), P(), P(), P("ranks")),
            out_specs=(P(), P(), P(), P()), check_vma=True),
            donate_argnums=(0, 1, 2))
    else:
        step = make_train_step(loss_fn, tx, mesh, sync_aux_state=True)

    data = shard_batch((images, labels), mesh)

    def run_once():
        nonlocal params, batch_stats, opt_state
        for _ in range(args.num_batches_per_iter):
            params, batch_stats, opt_state, loss = step(
                params, batch_stats, opt_state, data)
        np.asarray(loss)   # host read = hard sync

    print(f"Model: {args.model}, batch size (per rank): {args.batch_size}, "
          f"ranks: {n}, fusion: {not args.no_fusion}")
    for _ in range(max(1, args.num_warmup_batches //
                       args.num_batches_per_iter)):
        run_once()

    img_secs = []
    for i in range(args.num_iters):
        t0 = time.perf_counter()
        run_once()
        dt = time.perf_counter() - t0
        ips = batch * args.num_batches_per_iter / dt
        print(f"Iter #{i}: {ips:.1f} img/sec total")
        img_secs.append(ips / n)

    # Reporting format parity: mean ± 1.96σ per device and aggregate
    # (reference pytorch_synthetic_benchmark.py:93-110).
    img_sec_mean = np.mean(img_secs)
    img_sec_conf = 1.96 * np.std(img_secs)
    print(f"Img/sec per rank: {img_sec_mean:.1f} +-{img_sec_conf:.1f}")
    print(f"Total img/sec on {n} rank(s): {n * img_sec_mean:.1f} "
          f"+-{n * img_sec_conf:.1f}")


if __name__ == "__main__":
    main()
