"""Model-parallel training — tensor parallelism on a (dp, tp) mesh.

No reference counterpart (Horovod is data-parallel only); this example
shows the framework's model-sharding surface end to end:

1. shape-evaluate the TP model OUTSIDE the mesh (`tp_abstract_params`),
2. derive PartitionSpec trees for params and optax state
   (`tp_spec_tree`, `tp_optimizer_specs`),
3. initialize *materially sharded* params on the mesh (each chip holds
   its kernel slice — a layer tp-times too big for one chip fits),
4. train under ``shard_map(..., check_vma=True)`` with
   `tp_value_and_grad` (exact gradients, no manual reductions).

Usage:  python examples/jax_model_parallel.py --steps 100
        (needs an even number of visible chips; dp=2, tp=n/2)
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.parallel.mesh import build_mesh
from horovod_tpu.parallel.tensor_parallel import (
    TPMlp, tp_abstract_params, tp_optimizer_specs, tp_spec_tree,
    tp_value_and_grad)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--batch-size", type=int, default=16,
                   help="per-dp-shard batch size")
    p.add_argument("--dim", type=int, default=32)
    p.add_argument("--hidden-per-chip", type=int, default=64,
                   help="MLP hidden width per tp chip")
    p.add_argument("--lr", type=float, default=1e-2)
    args = p.parse_args()

    hvd.init()
    n = hvd.size()
    if n % 2:
        raise SystemExit("needs an even number of chips (dp=2)")
    dp, tp = 2, n // 2
    mesh = build_mesh(hvd.get_topology(), (dp, tp),
                      ("dp", "tp"))
    D = args.dim
    mlp = TPMlp(hidden=args.hidden_per_chip * tp, out=D, dtype=jnp.float32)
    tx = optax.adam(args.lr)

    # Steps 1-2: shapes and specs before touching the mesh.
    shapes = tp_abstract_params(
        lambda: mlp.init(jax.random.PRNGKey(1),
                         jnp.zeros((1, D)))["params"], tp)
    pspecs = tp_spec_tree(shapes)
    ospecs = tp_optimizer_specs(jax.eval_shape(tx.init, shapes),
                                shapes, pspecs)

    # Step 3: sharded init — each tp chip draws its own kernel slice.
    def init_body(x):
        params = mlp.init(jax.random.PRNGKey(1), x)["params"]
        return params, tx.init(params)

    # Step 4: the training step; tp_value_and_grad handles the dp mean.
    def step_body(params, opt_state, x, y):
        def loss_fn(p):
            return ((mlp.apply({"params": p}, x) - y) ** 2).mean()
        loss, grads = tp_value_and_grad(loss_fn, params, dp_axes=("dp",))
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    rng = np.random.RandomState(0)
    X = jnp.asarray(rng.randn(args.batch_size * dp, D), jnp.float32)
    Y = jnp.tanh(X @ jnp.asarray(rng.randn(D, D) * 0.5, jnp.float32))
    batch_sharding = NamedSharding(mesh, P("dp"))
    Xs = jax.device_put(X, batch_sharding)
    Ys = jax.device_put(Y, batch_sharding)

    params, opt_state = jax.jit(shard_map(
        init_body, mesh=mesh, in_specs=(P("dp"),),
        out_specs=(pspecs, ospecs), check_vma=True))(Xs)
    step = jax.jit(shard_map(
        step_body, mesh=mesh,
        in_specs=(pspecs, ospecs, P("dp"), P("dp")),
        out_specs=(pspecs, ospecs, P()), check_vma=True))

    losses = []
    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state, Xs, Ys)
        losses.append(float(np.asarray(loss)))
    kernel = params["col"]["kernel"]
    if hvd.rank() == 0:
        print(f"loss {losses[0]:.4f} -> {losses[-1]:.4f}")
        print(f"col kernel: global {kernel.shape}, "
              f"sharded {kernel.sharding.spec} over mesh {dict(mesh.shape)}")
    return losses


if __name__ == "__main__":
    main()
