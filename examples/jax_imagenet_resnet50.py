"""ImageNet ResNet-50 — TPU-native counterpart of the reference's
``examples/keras_imagenet_resnet50.py``: LR warmup + staircase schedule
callbacks, rank-0 checkpointing, restore-and-broadcast resume
(reference ``:64-103, 132-151``).

Data: an ImageNet-format numpy shard directory via ``--data``; without it a
synthetic generator keeps the example hermetic (the reference requires the
real dataset on disk).
"""

import argparse
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu import callbacks as hvd_callbacks
from horovod_tpu import checkpoint as hvd_checkpoint
from horovod_tpu.jax.spmd import make_train_step, shard_batch
from horovod_tpu.models import ResNet50


def synthetic_batches(global_batch, image_size, steps, seed):
    rng = np.random.RandomState(seed)
    for _ in range(steps):
        x = rng.randn(global_batch, image_size, image_size, 3).astype(
            np.float32)
        y = rng.randint(0, 1000, global_batch).astype(np.int32)
        yield x, y


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=90)
    p.add_argument("--batch-size", type=int, default=32,
                   help="per-rank batch size")
    p.add_argument("--base-lr", type=float, default=0.0125,
                   help="per-rank base LR (scaled by size, reference :107)")
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--wd", type=float, default=5e-5)
    p.add_argument("--warmup-epochs", type=int, default=5)
    p.add_argument("--checkpoint-dir", type=str, default="./checkpoints")
    p.add_argument("--steps-per-epoch", type=int, default=100,
                   help="synthetic-data steps per epoch")
    p.add_argument("--image-size", type=int, default=224)
    args = p.parse_args()

    hvd.init()
    mesh = hvd.ranks_mesh()
    n = hvd.size()
    global_batch = args.batch_size * n

    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    rng = jax.random.PRNGKey(0)
    sample = jnp.zeros((1, args.image_size, args.image_size, 3))
    variables = model.init(rng, sample, train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    # Optimizer: SGD + momentum + weight decay, LR scaled by size
    # (reference keras_imagenet_resnet50.py:105-112), hyperparams exposed
    # for the callbacks.
    tx = hvd.jax.DistributedOptimizer(
        optax.inject_hyperparams(
            lambda learning_rate, momentum: optax.chain(
                optax.add_decayed_weights(args.wd),
                optax.sgd(learning_rate, momentum=momentum)),
        )(learning_rate=args.base_lr * n, momentum=args.momentum),
        compression=hvd.Compression.bf16)
    opt_state = tx.init(params)

    def loss_fn(params, batch_stats, batch):
        imgs, lbls = batch
        logits, mut = model.apply(
            {"params": params, "batch_stats": batch_stats}, imgs,
            train=True, mutable=["batch_stats"])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, lbls).mean()
        return loss, mut["batch_stats"]

    train_step = make_train_step(loss_fn, tx, mesh)

    state = hvd_callbacks.TrainingState(
        params=params, opt_state=opt_state, aux_state=batch_stats)

    # Resume: agree on the epoch, restore on rank 0, broadcast everywhere
    # (reference keras_imagenet_resnet50.py:64-103 — there via
    # hvd.load_model + broadcast; here the state pytree broadcast does
    # both).  The optimizer state resumes too, so SGD momentum survives
    # a restart exactly as the reference's loaded optimizer does;
    # params-and-optimizer-only jobs can use checkpoint.save_model /
    # load_model(directory) instead, which also rebuilds the optimizer
    # from its persisted spec.
    ckpt_state = {"params": state.params, "batch_stats": state.aux_state,
                  "opt_state": state.opt_state}
    # optional_keys: checkpoints written before opt_state was added
    # still resume (momentum restarts fresh in that case).
    restored, resume_epoch = hvd_checkpoint.restore_and_broadcast(
        args.checkpoint_dir, ckpt_state, optional_keys=("opt_state",))
    state.params = restored["params"]
    state.aux_state = restored["batch_stats"]
    state.opt_state = restored["opt_state"]
    # The restored hyperparams carry the checkpoint's DECAYED lr; the
    # schedule callbacks below capture initial_lr at on_train_begin and
    # re-apply their multipliers per epoch, so the live hyperparams must
    # be reset to the configured base values — otherwise a resume past a
    # decay boundary double-applies the decay.  (Momentum buffers — the
    # actual optimizer STATE — stay restored.)
    hvd_callbacks.find_hyperparams(state.opt_state).update(
        hvd_callbacks.find_hyperparams(opt_state))

    cbs = hvd_callbacks.CallbackList(
        [
            hvd_callbacks.BroadcastGlobalVariablesCallback(0),
            hvd_callbacks.MetricAverageCallback(),
            # Warmup then staircase decay — the reference's exact schedule
            # (keras_imagenet_resnet50.py:114-121).
            hvd_callbacks.LearningRateWarmupCallback(
                warmup_epochs=args.warmup_epochs,
                steps_per_epoch=args.steps_per_epoch, verbose=1),
            hvd_callbacks.LearningRateScheduleCallback(
                multiplier=1.0, start_epoch=args.warmup_epochs,
                end_epoch=30),
            hvd_callbacks.LearningRateScheduleCallback(
                multiplier=1e-1, start_epoch=30, end_epoch=60),
            hvd_callbacks.LearningRateScheduleCallback(
                multiplier=1e-2, start_epoch=60, end_epoch=80),
            hvd_callbacks.LearningRateScheduleCallback(
                multiplier=1e-3, start_epoch=80),
        ],
        state, params={"steps": args.steps_per_epoch})

    cbs.on_train_begin()
    for epoch in range(resume_epoch + 1, args.epochs):
        cbs.on_epoch_begin(epoch)
        losses = []
        for b, (x, y) in enumerate(synthetic_batches(
                global_batch, args.image_size, args.steps_per_epoch,
                seed=epoch)):
            cbs.on_batch_begin(b)
            batch = shard_batch((x, y), mesh)
            state.params, state.aux_state, state.opt_state, loss = \
                train_step(state.params, state.aux_state, state.opt_state,
                           batch)
            losses.append(loss)
            cbs.on_batch_end(b)
        logs = {"loss": float(np.mean([np.asarray(l) for l in losses]))}
        cbs.on_epoch_end(epoch, logs=logs)
        # Rank-0-only checkpoint (reference convention, README step 6).
        hvd_checkpoint.save(
            args.checkpoint_dir,
            {"params": state.params, "batch_stats": state.aux_state,
             "opt_state": state.opt_state},
            epoch=epoch)
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss={logs['loss']:.4f} "
                  f"lr={logs.get('lr', float('nan')):.5f}")


if __name__ == "__main__":
    main()
