"""Estimator-style MNIST — TPU-native counterpart of the reference's
``examples/tensorflow_mnist_estimator.py``: a structured train/evaluate
loop driven by a model_fn, with the rank-0-only ``model_dir`` checkpoint
convention (``tensorflow_mnist_estimator.py:147`` — "save checkpoints only
on worker 0 to prevent other workers from corrupting them") and total
steps divided by world size (``:178``).

The Estimator here owns: auto-resume from the newest checkpoint in
``model_dir``, the broadcast-after-init/restore hook, periodic rank-0
checkpointing, and sharded evaluation — so the user script is just a
model_fn and two input_fns.

Usage:  python examples/jax_mnist_estimator.py --steps 200
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu import checkpoint as hvd_checkpoint
from horovod_tpu.jax.spmd import make_eval_step, make_train_step, shard_batch
from horovod_tpu.models import ConvNet


class Estimator:
    """Structured training loop over the framework's SPMD step.

    ``model_fn(params, batch) -> (loss, predictions)``; ``params`` created
    by ``init_fn(rng)``.  ``model_dir`` follows the reference's estimator
    convention: pass a path on every rank — only rank 0 writes, every rank
    restores via rank-0-read + broadcast.
    """

    def __init__(self, init_fn, model_fn, optimizer, model_dir=None,
                 checkpoint_every=0):
        hvd.init()
        self.mesh = hvd.ranks_mesh()
        self.model_fn = model_fn
        self.model_dir = model_dir
        self.checkpoint_every = checkpoint_every
        self.tx = hvd.jax.DistributedOptimizer(optimizer)
        self.params = init_fn(jax.random.PRNGKey(0))
        self.opt_state = self.tx.init(self.params)
        self.global_step = 0

        def loss_fn(params, aux, batch):
            loss, _ = model_fn(params, batch)
            return loss, aux

        self._train_step = make_train_step(loss_fn, self.tx, self.mesh)

        def metrics_fn(params, aux, batch):
            loss, preds = model_fn(params, batch)
            _, labels = batch
            return {"loss": loss,
                    "accuracy": jnp.mean(preds == labels)}

        self._eval_step = make_eval_step(metrics_fn, self.mesh)

        # Auto-resume: rank 0 scans/restores, state broadcast to all ranks
        # (restore_and_broadcast broadcasts even when nothing was found, so
        # a fresh init is also rank-consistent).
        if model_dir:
            restored, resume = hvd_checkpoint.restore_and_broadcast(
                model_dir, {"params": self.params,
                            "opt_state": self.opt_state,
                            "global_step": np.asarray(0, np.int64)})
            self.params = restored["params"]
            self.opt_state = restored["opt_state"]
            if resume >= 0:
                self.global_step = int(np.asarray(restored["global_step"]))
        else:
            self.params = hvd.jax.broadcast_parameters(
                self.params, root_rank=0)

    def _save(self):
        if self.model_dir:
            hvd_checkpoint.save(
                self.model_dir,
                {"params": self.params, "opt_state": self.opt_state,
                 "global_step": self.global_step},
                self.global_step)

    def train(self, input_fn, steps):
        """Run ``steps // size`` optimizer steps (reference ``:178`` scales
        total work by world size); ``input_fn(step) -> global batch``."""
        local_steps = max(1, steps // hvd.size())
        for _ in range(local_steps):
            batch = shard_batch(input_fn(self.global_step), self.mesh)
            self.params, _, self.opt_state, loss = self._train_step(
                self.params, {}, self.opt_state, batch)
            self.global_step += 1
            if (self.checkpoint_every
                    and self.global_step % self.checkpoint_every == 0):
                self._save()
        self._save()
        return {"loss": float(np.asarray(loss)),
                "global_step": self.global_step}

    def evaluate(self, input_fn, steps):
        totals = {}
        for step in range(steps):
            batch = shard_batch(input_fn(step), self.mesh)
            m = self._eval_step(self.params, {}, batch)
            for k, v in m.items():
                totals.setdefault(k, []).append(float(np.asarray(v)))
        return {k: float(np.mean(v)) for k, v in totals.items()}


def load_data():
    rng = np.random.RandomState(0)
    n_train, n_test = 8192, 1024
    y = rng.randint(0, 10, n_train + n_test)
    x = rng.randn(n_train + n_test, 28, 28).astype(np.float32) * 0.1
    for c in range(10):
        mask = y == c
        x[mask, c * 2:(c * 2) + 4, c * 2:(c * 2) + 4] += 1.0
    return (x[:n_train], y[:n_train].astype(np.int32),
            x[n_train:], y[n_train:].astype(np.int32))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=200,
                   help="total train steps across all ranks")
    p.add_argument("--batch-size", type=int, default=64,
                   help="per-rank batch size")
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--model-dir", type=str, default="")
    p.add_argument("--checkpoint-every", type=int, default=50)
    args = p.parse_args()

    hvd.init()
    n = hvd.size()
    global_batch = args.batch_size * n
    train_x, train_y, test_x, test_y = load_data()

    model = ConvNet()

    def init_fn(rng):
        return model.init(rng, jnp.zeros((1, 28, 28, 1)))["params"]

    def model_fn(params, batch):
        imgs, lbls = batch
        logits = model.apply({"params": params}, imgs[..., None])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, lbls).mean()
        return loss, jnp.argmax(logits, -1)

    est = Estimator(init_fn, model_fn,
                    optax.sgd(args.lr * n, momentum=0.9),
                    model_dir=args.model_dir or None,
                    checkpoint_every=args.checkpoint_every)

    rng = np.random.RandomState(est.global_step + 1)

    def train_input_fn(step):
        idx = rng.randint(0, len(train_x), global_batch)
        return train_x[idx], train_y[idx]

    def eval_input_fn(step):
        sl = slice(step * global_batch, (step + 1) * global_batch)
        return test_x[sl], test_y[sl]

    result = est.train(train_input_fn, steps=args.steps)
    metrics = est.evaluate(eval_input_fn, steps=len(test_x) // global_batch)
    if hvd.rank() == 0:
        print(f"global_step={result['global_step']} "
              f"eval_loss={metrics['loss']:.4f} "
              f"eval_accuracy={metrics['accuracy']:.4f}")
    return metrics["accuracy"]


if __name__ == "__main__":
    main()
