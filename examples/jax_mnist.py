"""MNIST training — TPU-native counterpart of the reference's MNIST
examples (``examples/tensorflow_mnist.py``, ``examples/pytorch_mnist.py``):
same 4-step recipe (init → shard data by rank → wrap optimizer →
broadcast initial state), ConvNet model, per-epoch metric averaging.

Runs on real MNIST if an ``mnist.npz`` is available locally (set
``--data``), else on a deterministic synthetic stand-in so the example is
runnable in hermetic environments (no download at import time, unlike the
reference which fetches the dataset).

Usage:  python examples/jax_mnist.py --epochs 2
        (multi-chip: runs data-parallel over every visible TPU chip)
"""

import argparse
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu import callbacks as hvd_callbacks
from horovod_tpu.data import ShardedLoader, epoch_batches
from horovod_tpu.jax.spmd import make_train_step
from horovod_tpu.models import ConvNet


def load_data(path):
    """(train_x, train_y, test_x, test_y) in [0,1] NHWC."""
    if path and os.path.exists(path):
        with np.load(path) as d:
            return (d["x_train"].astype(np.float32) / 255.0, d["y_train"],
                    d["x_test"].astype(np.float32) / 255.0, d["y_test"])
    # Synthetic stand-in: class-dependent blobs, learnable to high accuracy.
    rng = np.random.RandomState(0)
    n_train, n_test = 8192, 1024
    y = rng.randint(0, 10, n_train + n_test)
    x = rng.randn(n_train + n_test, 28, 28).astype(np.float32) * 0.1
    for c in range(10):
        mask = y == c
        x[mask, c * 2:(c * 2) + 4, c * 2:(c * 2) + 4] += 1.0
    return x[:n_train], y[:n_train], x[n_train:], y[n_train:]


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=64,
                   help="per-rank batch size")
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--momentum", type=float, default=0.5)
    p.add_argument("--data", type=str, default="",
                   help="path to an mnist.npz; synthetic data if absent")
    args = p.parse_args()

    # Step 1: initialize from the pod topology (no mpirun).
    hvd.init()
    mesh = hvd.ranks_mesh()
    n = hvd.size()
    global_batch = args.batch_size * n

    train_x, train_y, test_x, test_y = load_data(args.data)

    model = ConvNet()
    rng = jax.random.PRNGKey(0)
    params = model.init(rng, jnp.zeros((1, 28, 28, 1)))["params"]

    # Step 3: wrap the optimizer; LR scaled by size per the reference recipe
    # (README step 3), warmup ramps into it.  inject_hyperparams exposes
    # lr/momentum to the callbacks.
    tx = hvd.jax.DistributedOptimizer(
        optax.inject_hyperparams(optax.sgd)(
            learning_rate=args.lr * n, momentum=args.momentum))
    opt_state = tx.init(params)

    def loss_fn(params, aux, batch):
        imgs, lbls = batch
        logits = model.apply({"params": params}, imgs[..., None])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, lbls).mean(), aux

    train_step = make_train_step(loss_fn, tx, mesh)

    state = hvd_callbacks.TrainingState(params=params, opt_state=opt_state)
    # Derive steps_per_epoch exactly as the loader batches: per-process
    # rows (n // P) over per-process batch (global_batch // P).  The
    # naive len(train_x) // global_batch drifts from the real step count
    # whenever P does not divide global_batch or n, and the warmup
    # schedule would follow the wrong clock.
    per_proc_batch = global_batch // hvd.process_count()
    steps_per_epoch = (len(train_x) // hvd.process_count()) // per_proc_batch
    cbs = hvd_callbacks.CallbackList(
        [
            # Step 4: broadcast initial state from rank 0.
            hvd_callbacks.BroadcastGlobalVariablesCallback(0),
            hvd_callbacks.MetricAverageCallback(),
            hvd_callbacks.LearningRateWarmupCallback(
                warmup_epochs=1, steps_per_epoch=steps_per_epoch, verbose=1),
        ],
        state, params={"steps": steps_per_epoch})

    cbs.on_train_begin()
    train_y32 = train_y.astype(np.int32)
    for epoch in range(args.epochs):
        cbs.on_epoch_begin(epoch)
        # Step 2 of the recipe: DistributedSampler-style epoch shard —
        # identical shuffle everywhere, process-strided rows, equal batch
        # counts (horovod_tpu.data; reference pytorch_mnist.py:98-103).
        # Each process stages its share of the global batch;
        # shard_for_process (inside ShardedLoader) assembles the global
        # sharded array, and the prefetch thread stays a step ahead.
        loader = ShardedLoader(
            lambda e=epoch: epoch_batches(
                train_x, train_y32,
                global_batch // hvd.process_count(),
                rank=hvd.process_index(), size=hvd.process_count(),
                seed=1234 + e),
            mesh)
        losses = []
        for b, batch in enumerate(loader):
            cbs.on_batch_begin(b)
            state.params, _, state.opt_state, loss = train_step(
                state.params, {}, state.opt_state, batch)
            losses.append(loss)
            cbs.on_batch_end(b)
        logs = {"loss": float(np.mean([np.asarray(l) for l in losses]))}
        cbs.on_epoch_end(epoch, logs=logs)
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss={logs['loss']:.4f} "
                  f"lr={logs.get('lr', float('nan')):.4f}")

    # Eval (rank-replicated; metric averaged across ranks for parity with
    # pytorch_mnist.py's metric_average, :44-125).
    logits = model.apply({"params": state.params},
                         jnp.asarray(test_x)[..., None])
    acc = float(np.mean(np.argmax(np.asarray(logits), -1) == test_y))
    acc = float(np.asarray(hvd.allreduce(np.float32(acc), average=True,
                                         name="test.accuracy")))
    if hvd.rank() == 0:
        print(f"test accuracy: {acc:.4f}")
    return acc


if __name__ == "__main__":
    main()
