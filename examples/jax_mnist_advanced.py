"""Advanced MNIST — TPU-native counterpart of the reference's
``examples/keras_mnist_advanced.py``: data augmentation with **per-rank
random streams**, the full callback stack (broadcast, metric averaging,
LR warmup), and rank-0-only checkpointing.

Where the reference seeds a separate host-side ``ImageDataGenerator`` per
worker (``keras_mnist_advanced.py:105-121``), the TPU-native version
compiles augmentation *into the training step*: each shard derives its
stream by folding ``lax.axis_index`` (its rank) and the step counter into
the replicated PRNG key, so every rank sees distinct augmentations with no
host-side pipeline at all — the random shifts/scales fuse into the same
XLA program as the forward pass.

Usage:  python examples/jax_mnist_advanced.py --epochs 4
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

import horovod_tpu as hvd
from horovod_tpu import callbacks as hvd_callbacks
from horovod_tpu import checkpoint as hvd_checkpoint
from horovod_tpu.jax.spmd import make_eval_step, make_train_step, shard_batch
from horovod_tpu.models import ConvNet

MAX_SHIFT = 3        # random translation, pixels (reference uses ~8% ≈ 2.2)
SCALE_RANGE = 0.08   # random brightness/zoom-like multiplicative jitter


def augment(key, images):
    """Random shift + multiplicative jitter, static shapes throughout.

    Per-image keys via vmap; shift implemented as pad + dynamic_slice so
    XLA lowers it to cheap HBM addressing rather than a gather.
    """
    n, h, w = images.shape[:3]
    keys = jax.random.split(key, n)

    def one(k, img):
        k_shift, k_scale = jax.random.split(k)
        dy, dx = jax.random.randint(k_shift, (2,), 0, 2 * MAX_SHIFT + 1)
        padded = jnp.pad(img, ((MAX_SHIFT, MAX_SHIFT),
                               (MAX_SHIFT, MAX_SHIFT)))
        img = lax.dynamic_slice(padded, (dy, dx), (h, w))
        scale = 1.0 + jax.random.uniform(
            k_scale, (), minval=-SCALE_RANGE, maxval=SCALE_RANGE)
        return img * scale

    return jax.vmap(one)(keys, images)


def load_data():
    """Deterministic synthetic MNIST stand-in (hermetic; no downloads).

    The class signal is blob *size* (shift-invariant), so random-shift
    augmentation makes the task harder without making it ambiguous.
    """
    rng = np.random.RandomState(0)
    n_train, n_test = 8192, 1024
    y = rng.randint(0, 10, n_train + n_test)
    x = rng.randn(n_train + n_test, 28, 28).astype(np.float32) * 0.1
    for c in range(10):
        mask = y == c
        sz = 2 * c + 2
        x[mask, 4:4 + sz, 4:4 + sz] += 1.0
    return (x[:n_train], y[:n_train].astype(np.int32),
            x[n_train:], y[n_train:].astype(np.int32))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=4)
    p.add_argument("--batch-size", type=int, default=64,
                   help="per-rank batch size")
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--warmup-epochs", type=int, default=2)
    p.add_argument("--checkpoint-dir", type=str, default="")
    args = p.parse_args()

    hvd.init()
    mesh = hvd.ranks_mesh()
    n = hvd.size()
    global_batch = args.batch_size * n

    train_x, train_y, test_x, test_y = load_data()
    model = ConvNet()
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 28, 28, 1)))["params"]

    tx = hvd.jax.DistributedOptimizer(
        optax.inject_hyperparams(optax.sgd)(
            learning_rate=args.lr * n, momentum=0.9))
    opt_state = tx.init(params)

    axis = tuple(mesh.axis_names)

    def loss_fn(params, aux, batch):
        imgs, lbls = batch
        # Per-rank stream: fold (rank, step) into the replicated key.  The
        # TPU-native analogue of the reference's per-worker generator seed.
        key = jax.random.fold_in(
            jax.random.fold_in(aux["key"], lax.axis_index(axis)),
            aux["step"])
        imgs = augment(key, imgs)
        logits = model.apply({"params": params}, imgs[..., None])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, lbls).mean()
        return loss, {"key": aux["key"], "step": aux["step"] + 1}

    train_step = make_train_step(loss_fn, tx, mesh)

    def eval_metrics(params, aux, batch):
        imgs, lbls = batch
        logits = model.apply({"params": params}, imgs[..., None])
        return {"accuracy": jnp.mean(jnp.argmax(logits, -1) == lbls)}

    eval_step = make_eval_step(eval_metrics, mesh)

    state = hvd_callbacks.TrainingState(params=params, opt_state=opt_state)
    steps_per_epoch = len(train_x) // global_batch
    cbs = hvd_callbacks.CallbackList(
        [
            hvd_callbacks.BroadcastGlobalVariablesCallback(0),
            hvd_callbacks.MetricAverageCallback(),
            hvd_callbacks.LearningRateWarmupCallback(
                warmup_epochs=args.warmup_epochs,
                steps_per_epoch=steps_per_epoch, verbose=1),
        ],
        state, params={"steps": steps_per_epoch})

    aux = {"key": jax.random.PRNGKey(42), "step": jnp.int32(0)}
    rng_np = np.random.RandomState(1234)
    cbs.on_train_begin()
    for epoch in range(args.epochs):
        cbs.on_epoch_begin(epoch)
        perm = rng_np.permutation(len(train_x))
        losses = []
        for b in range(steps_per_epoch):
            cbs.on_batch_begin(b)
            idx = perm[b * global_batch:(b + 1) * global_batch]
            batch = shard_batch((train_x[idx], train_y[idx]), mesh)
            state.params, aux, state.opt_state, loss = train_step(
                state.params, aux, state.opt_state, batch)
            losses.append(loss)
            cbs.on_batch_end(b)
        logs = {"loss": float(np.mean([np.asarray(l) for l in losses]))}
        cbs.on_epoch_end(epoch, logs=logs)
        # Rank-0-only checkpointing (reference convention, README step 6);
        # other ranks no-op inside save().
        if args.checkpoint_dir:
            hvd_checkpoint.save(
                args.checkpoint_dir,
                {"params": state.params, "opt_state": state.opt_state},
                epoch)
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss={logs['loss']:.4f} "
                  f"lr={logs.get('lr', float('nan')):.4f}")

    n_eval = (len(test_x) // global_batch) * global_batch
    accs = []
    for b in range(n_eval // global_batch):
        sl = slice(b * global_batch, (b + 1) * global_batch)
        m = eval_step(state.params, {},
                      shard_batch((test_x[sl], test_y[sl]), mesh))
        accs.append(float(np.asarray(m["accuracy"])))
    acc = float(np.mean(accs))
    if hvd.rank() == 0:
        print(f"test accuracy: {acc:.4f}")
    return acc


if __name__ == "__main__":
    main()
