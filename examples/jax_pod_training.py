"""Multi-controller pod training — the zero-config flagship path.

The reference needs ``mpirun`` on every host; on a TPU pod the runtime
already starts one process per host, so this script needs NO launcher and
NO environment: each process calls ``jax.distributed.initialize()`` (a
no-op when single-process), ``hvd.init()`` resolves the global topology,
and ``make_train_step`` compiles the whole step — forward, backward,
cross-host gradient allreduce over ICI/DCN, optimizer update — into one
XLA program per process (docs/running.md "Multi-controller pods").

Each process feeds ONLY its local shard of the global batch
(``jax.make_array_from_process_local_data``) — the multi-controller
input-pipeline contract — yet the loss trajectory is identical to a
single-process run of the same global batch (asserted by
``tests/test_multicontroller.py``, which runs this path across two real
OS processes).

Runs as-is on one process too (e.g. this repo's CI), where it degrades to
ordinary data parallelism over the visible chips.

Usage:  python examples/jax_pod_training.py --steps 30
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
import horovod_tpu.jax as hvd_jax
from horovod_tpu.jax.spmd import make_train_step
from horovod_tpu.models import MLP


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch-per-rank", type=int, default=32)
    ap.add_argument("--lr", type=float, default=0.05)
    args = ap.parse_args()
    if args.steps < 1:
        ap.error("--steps must be >= 1")

    try:
        # On a pod the runtime env tells every process where the
        # coordinator is; single-process this raises and is skipped.
        jax.distributed.initialize()
    except Exception:   # noqa: BLE001 — inspect before swallowing
        # Only swallow when NO cluster was configured (plain single-host
        # run).  A configured-but-failing pod must raise: silently
        # degrading to N independent single-host runs would train N
        # divergent models with no error.  Markers cover explicit
        # coordinator env plus the launchers JAX auto-detects (Cloud TPU
        # metadata, Slurm, Open MPI).
        import os
        def _ntasks(v):
            # Values like Slurm's "2(x2)" are not plain ints; treat
            # anything unparseable as not-configured rather than crash
            # inside this except handler.
            raw = (os.environ.get(v) or "").strip()
            return int(raw) if raw.isdigit() else 1

        multi_task = any(
            _ntasks(v) > 1
            for v in ("SLURM_NTASKS", "SLURM_NPROCS",
                      "SLURM_STEP_NUM_TASKS", "OMPI_COMM_WORLD_SIZE"))
        # TPU_WORKER_HOSTNAMES exists on single-host TPU VMs too; only
        # >1 comma-separated workers indicate a pod.
        multi_host_tpu = len([h for h in os.environ.get(
            "TPU_WORKER_HOSTNAMES", "").split(",") if h]) > 1
        if multi_task or multi_host_tpu or any(os.environ.get(v) for v in (
                "JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
                "MEGASCALE_COORDINATOR_ADDRESS")):
            raise

    hvd.init()
    mesh = hvd.ranks_mesh()
    n = hvd.size()
    if hvd.rank() == 0:
        print(f"pod: {hvd.process_count()} process(es), {n} chips")

    # Deterministic synthetic regression task, identical on every process.
    rng = np.random.RandomState(0)
    d_in, d_out = 16, 4
    w_true = rng.randn(d_in, d_out).astype(np.float32)
    batch = args.batch_per_rank * n
    x_global = rng.randn(batch, d_in).astype(np.float32)
    y_global = x_global @ w_true

    model = MLP(features=(64,), num_classes=d_out)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, d_in)))["params"]
    # Startup sync (reference recipe step 4): identical initial state.
    params = hvd_jax.broadcast_parameters(params, root_rank=0)

    def loss_fn(params, aux, data):
        x, y = data
        pred = model.apply({"params": params}, x)
        return jnp.mean((pred - y) ** 2), aux

    tx = optax.sgd(args.lr)
    opt_state = tx.init(params)
    step = make_train_step(loss_fn, tx, mesh, sync_aux_state=False)

    # Multi-controller input contract: each process supplies only the rows
    # owned by ITS ranks; shard_for_process assembles the global array
    # (plain sharded device_put when single-controller).
    from horovod_tpu.data import shard_for_process
    rows = batch // hvd.process_count()
    lo = hvd.process_index() * rows
    x, y = shard_for_process(
        (x_global[lo:lo + rows], y_global[lo:lo + rows]), mesh)

    aux = {}
    loss0 = loss = None
    for i in range(args.steps):
        params, aux, opt_state, loss = step(params, aux, opt_state, (x, y))
        if loss0 is None:
            loss0 = float(loss)
        if hvd.rank() == 0 and i % 10 == 0:
            print(f"step {i:4d}  loss {float(loss):.6f}")
    final = float(loss)
    if hvd.rank() == 0:
        print(f"final loss {final:.6f} (from {loss0:.6f})")
    return loss0, final


if __name__ == "__main__":
    main()
