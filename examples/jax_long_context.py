"""Long-context LM training with sequence parallelism.

No counterpart exists in the reference (it is data-parallel only,
SURVEY §5.7) — this example shows the framework's long-context story: a
causal transformer whose sequence dimension is sharded across the chip mesh,
with attention running as a K/V ring over ICI (``--attn ring``, or
``--attn ring_zigzag`` for the causal-load-balanced layout) or via
all-to-all head re-sharding (``--attn ulysses``).

Memory scaling: with ring attention, per-chip attention memory is
O(T/n × T/n) per block, so context length scales linearly with chips.
Ulysses keeps activations at O(T/n) but its default local kernel
materializes full T×T logits for this rank's head subset — use
``--attn ulysses_flash`` to run the local attention through the Pallas
flash kernel instead (linear memory, docs/long-context.md).
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.jax.spmd import make_train_step
from horovod_tpu.models import TransformerLM


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--attn", default="ring",
                   choices=["ring", "ring_zigzag", "ulysses",
                            "ulysses_flash"])
    p.add_argument("--seq-len", type=int, default=8192,
                   help="GLOBAL sequence length (sharded over chips)")
    p.add_argument("--batch-size", type=int, default=1,
                   help="global batch size")
    p.add_argument("--vocab", type=int, default=32000)
    p.add_argument("--dim", type=int, default=512)
    p.add_argument("--depth", type=int, default=8)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--lr", type=float, default=3e-4)
    args = p.parse_args()

    hvd.init()
    n = hvd.size()
    mesh = hvd.ranks_mesh()
    assert args.seq_len % n == 0, "seq-len must divide across chips"
    if args.attn.startswith("ulysses"):
        assert args.heads % n == 0, "ulysses shards heads across chips"

    model = TransformerLM(
        vocab=args.vocab, dim=args.dim, depth=args.depth,
        num_heads=args.heads, max_len=args.seq_len, attn=args.attn,
        sp_axis="ranks")
    twin = TransformerLM(
        vocab=args.vocab, dim=args.dim, depth=args.depth,
        num_heads=args.heads, max_len=args.seq_len, attn="full")
    params = twin.init(jax.random.PRNGKey(0),
                       jnp.zeros((1, 8), jnp.int32))["params"]
    params = hvd.jax.broadcast_parameters(params)
    tx = optax.adamw(args.lr)
    opt_state = tx.init(params)

    def loss_fn(p, aux, batch):
        tokens, labels = batch
        logits = model.apply({"params": p}, tokens)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean(), aux

    fn = make_train_step(loss_fn, tx, mesh, batch_spec=P(None, "ranks"))

    rng = np.random.RandomState(0)
    spec = NamedSharding(mesh, P(None, "ranks"))
    if args.attn == "ring_zigzag":
        # Zigzag layout: fixed host-side permutation of the sequence so
        # each chip holds chunks (r, 2n-1-r) — the causal-balanced
        # schedule (docs/long-context.md).  Labels permute identically,
        # so the mean LM loss is unchanged.
        from horovod_tpu.parallel.ring_attention import zigzag_indices
        zz = zigzag_indices(n, args.seq_len)
    aux = {}
    t0 = time.perf_counter()
    for i in range(args.steps):
        toks = rng.randint(0, args.vocab,
                           (args.batch_size, args.seq_len + 1)).astype(
            np.int32)
        x, y = toks[:, :-1], toks[:, 1:]
        if args.attn == "ring_zigzag":
            x, y = x[:, zz], y[:, zz]
        tokens = jax.device_put(x, spec)
        labels = jax.device_put(y, spec)
        params, aux, opt_state, loss = fn(params, aux, opt_state,
                                          (tokens, labels))
        if hvd.rank() == 0 and i % 5 == 0:
            print(f"step {i}: loss={float(np.asarray(loss)):.4f}")
    np.asarray(loss)
    if hvd.rank() == 0:
        dt = time.perf_counter() - t0
        tps = args.steps * args.batch_size * args.seq_len / dt
        print(f"{args.attn} attention, seq {args.seq_len} over {n} chips: "
              f"{tps:.0f} tokens/sec")


if __name__ == "__main__":
    main()
