"""word2vec skip-gram — TPU-native counterpart of the reference's
``examples/tensorflow_word2vec.py``: the embedding gradient takes the
**sparse allgather path** (reference ``horovod/tensorflow/__init__.py:67-78``)
instead of a dense allreduce over the whole vocabulary.

Design: the forward gathers only the touched embedding rows; the backward
produces gradients for those rows, which are handed to the stock
``DistributedOptimizer`` as ``IndexedSlices`` — the wrapper routes them
through the sparse allgather automatically (rows+indices over the rank
mesh, comm cost ∝ batch size, not vocab size) and scatters to dense only
locally for the optax update.  ``sparse_as_dense=True`` would densify
before a regular allreduce instead, like the reference's escape hatch.

Corpus: synthetic Zipf-distributed token stream (the reference downloads
text8; this stays hermetic).
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax, shard_map
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import sparse
from horovod_tpu.jax.spmd import shard_batch


def make_corpus(vocab, n_tokens, seed=0):
    rng = np.random.RandomState(seed)
    # Zipf-ish unigram distribution like natural text.
    p = 1.0 / np.arange(1, vocab + 1)
    p /= p.sum()
    return rng.choice(vocab, size=n_tokens, p=p).astype(np.int32)


def skipgram_pairs(corpus, window, batch, rng):
    centers = rng.randint(window, len(corpus) - window, batch)
    offs = rng.randint(1, window + 1, batch) * rng.choice([-1, 1], batch)
    return corpus[centers], corpus[centers + offs]


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--vocab", type=int, default=5000)
    p.add_argument("--dim", type=int, default=128)
    p.add_argument("--batch-size", type=int, default=128,
                   help="per-rank batch size")
    p.add_argument("--neg", type=int, default=8,
                   help="negative samples per pair (NCE-style)")
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--window", type=int, default=2)
    p.add_argument("--lr", type=float, default=0.5)
    args = p.parse_args()

    hvd.init()
    mesh = hvd.ranks_mesh()
    n = hvd.size()
    global_batch = args.batch_size * n

    rng = np.random.RandomState(hash("w2v") % (2 ** 31))
    corpus = make_corpus(args.vocab, 200_000)

    key = jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(key)
    emb_in = jax.random.uniform(k1, (args.vocab, args.dim),
                                minval=-0.5 / args.dim,
                                maxval=0.5 / args.dim)
    emb_out = jax.random.uniform(k2, (args.vocab, args.dim),
                                 minval=-0.5 / args.dim,
                                 maxval=0.5 / args.dim)
    # Step 4 of the recipe: all ranks start from identical tables.
    emb_in, emb_out = hvd.jax.broadcast_parameters((emb_in, emb_out))
    params = {"emb_in": emb_in, "emb_out": emb_out}

    # The stock wrapper: IndexedSlices gradient leaves take the sparse
    # allgather path inside its update — no manual sparse.allreduce.
    tx = hvd.jax.DistributedOptimizer(optax.sgd(args.lr))
    opt_state = tx.init(params)

    def step_body(params, opt_state, centers, contexts, negs):
        """One sparse SGD step under shard_map (centers/contexts/negs are
        this rank's shard)."""
        emb_in, emb_out = params["emb_in"], params["emb_out"]
        c_rows = emb_in[centers]               # (B, D) touched rows only
        ctx_rows = emb_out[contexts]           # (B, D)
        neg_rows = emb_out[negs]               # (B, K, D)

        def loss_of(rows):
            c, ctx, neg = rows
            pos_logit = jnp.sum(c * ctx, axis=-1)
            neg_logit = jnp.einsum("bd,bkd->bk", c, neg)
            pos_loss = jax.nn.softplus(-pos_logit)
            neg_loss = jax.nn.softplus(neg_logit).sum(-1)
            return (pos_loss + neg_loss).mean()

        loss, (g_c, g_ctx, g_neg) = jax.value_and_grad(loss_of)(
            (c_rows, ctx_rows, neg_rows))

        # Row-gradients as IndexedSlices; both emb_out contributions
        # (context + negatives) concatenate into one slice-set —
        # duplicate indices sum, the IndexedSlices contract.
        grads = {
            "emb_in": sparse.IndexedSlices(g_c, centers, emb_in.shape),
            "emb_out": sparse.IndexedSlices(
                jnp.concatenate([g_ctx, g_neg.reshape(-1, g_neg.shape[-1])]),
                jnp.concatenate([contexts, negs.reshape(-1)]),
                emb_out.shape),
        }
        updates, opt_state2 = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state2, lax.pmean(loss, "ranks")

    # check_vma=False is deliberate here: the sparse path allgathers
    # (rows, indices) and scatter-adds the identical gathered data on every
    # rank, so the embedding update is invariant by construction — but an
    # all_gather output is *tracked* varying, which the checker cannot see
    # past.  The dense training paths all run checked (make_train_step).
    step = jax.jit(shard_map(
        step_body, mesh=mesh,
        in_specs=(P(), P(), P("ranks"), P("ranks"), P("ranks")),
        out_specs=(P(), P(), P()), check_vma=False),
        donate_argnums=(0, 1))

    t0 = time.perf_counter()
    for i in range(args.steps):
        centers, contexts = skipgram_pairs(corpus, args.window, global_batch,
                                           rng)
        negs = rng.randint(0, args.vocab,
                           (global_batch, args.neg)).astype(np.int32)
        centers, contexts, negs = shard_batch(
            (centers, contexts, negs), mesh)
        params, opt_state, loss = step(params, opt_state, centers, contexts,
                                       negs)
        if i % 50 == 0 and hvd.rank() == 0:
            print(f"step {i}: loss={float(np.asarray(loss)):.4f}")
    if hvd.rank() == 0:
        dt = time.perf_counter() - t0
        print(f"{args.steps} steps in {dt:.2f}s "
              f"({args.steps * global_batch / dt:.0f} pairs/sec); "
              f"final loss {float(np.asarray(loss)):.4f}")


if __name__ == "__main__":
    main()
