"""Pipeline-parallel transformer — GPipe stages on a ``pp`` mesh.

No reference counterpart (Horovod is data-parallel only).  The depth of a
transformer LM is partitioned across chips: each chip owns a
:class:`~horovod_tpu.models.BlockStack` of ``depth_per_stage`` blocks and
microbatches stream through the stages
(:mod:`horovod_tpu.parallel.pipeline`).  The token embedding and LM head
stay replicated outside the pipeline — cheap relative to the blocks, and
it keeps stage activations shape-uniform.

The whole training run is ONE jitted program: init + a ``lax.scan`` over
optimizer steps inside ``shard_map`` — per-stage params and optimizer
state live sharded on their chips for the entire run and never visit the
host (the losses, pp-invariant after the pipeline's output psum, are the
only thing returned).

Usage:  python examples/jax_pipeline_transformer.py --steps 40
        (stages = number of visible chips)
"""

import argparse

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax, shard_map
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models import BlockStack
from horovod_tpu.parallel.mesh import build_mesh
from horovod_tpu.parallel.pipeline import (microbatch, pipeline_apply,
                                           stage_params_init, unmicrobatch)


class EmbedHead(nn.Module):
    """The replicated ends of the LM: token+position embedding and head."""

    vocab: int
    dim: int
    max_len: int = 2048

    def setup(self):
        self.tok = nn.Embed(self.vocab, self.dim, param_dtype=jnp.float32,
                            name="tok_emb")
        self.pos = nn.Embed(self.max_len, self.dim,
                            param_dtype=jnp.float32, name="pos_emb")
        self.ln_f = nn.LayerNorm(dtype=jnp.float32, name="ln_f")
        self.head = nn.Dense(self.vocab, use_bias=False,
                             dtype=jnp.float32, name="head")

    def embed(self, tokens):
        B, T = tokens.shape
        return self.tok(tokens) + self.pos(jnp.arange(T))[None]

    def logits(self, x):
        return self.head(self.ln_f(x))

    def __call__(self, tokens):
        # Touches every submodule so plain init creates all params.
        return self.logits(self.embed(tokens))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=40)
    p.add_argument("--vocab", type=int, default=64)
    p.add_argument("--dim", type=int, default=32)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--depth-per-stage", type=int, default=1)
    p.add_argument("--seq-len", type=int, default=16)
    p.add_argument("--microbatches", type=int, default=0,
                   help="default: 2x stages")
    p.add_argument("--lr", type=float, default=1e-2)
    args = p.parse_args()

    hvd.init()
    S = hvd.size()
    mesh = build_mesh(hvd.get_topology(), (S,), ("pp",))
    M = args.microbatches or 2 * S
    mb = 2
    T = args.seq_len

    ends = EmbedHead(vocab=args.vocab, dim=args.dim)
    stage = BlockStack(num_heads=args.heads, depth=args.depth_per_stage,
                       dtype=jnp.float32)
    tx = optax.adam(args.lr)

    rng = np.random.RandomState(0)
    toks = rng.randint(0, args.vocab, (M * mb, T + 1)).astype(np.int32)
    x_host, y_host = jnp.asarray(toks[:, :-1]), jnp.asarray(toks[:, 1:])

    def stage_fn(params, h):
        return stage.apply({"params": params}, h)

    def loss_of(params, x, y):
        h = ends.apply({"params": params["ends"]}, x,
                       method=EmbedHead.embed)
        h = unmicrobatch(pipeline_apply(stage_fn, params["stages"],
                                        microbatch(h, M)))
        logits = ends.apply({"params": params["ends"]}, h,
                            method=EmbedHead.logits)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()

    def train_body(x, y):
        params = {
            "ends": ends.init(jax.random.PRNGKey(0), x)["params"],
            # One BlockStack per pp chip, distinct params per stage.
            "stages": stage_params_init(
                lambda k: stage.init(
                    k, jnp.zeros((mb, T, args.dim), jnp.float32))["params"],
                jax.random.PRNGKey(1)),
        }
        opt_state = tx.init(params)

        def one_step(carry, _):
            params, opt_state = carry
            loss, grads = jax.value_and_grad(loss_of)(params, x, y)
            updates, opt_state = tx.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state), loss

        _, losses = lax.scan(one_step, (params, opt_state), None,
                             length=args.steps)
        return losses

    fn = jax.jit(shard_map(train_body, mesh=mesh, in_specs=(P(), P()),
                           out_specs=P(), check_vma=True))
    losses = np.asarray(fn(x_host, y_host))
    if hvd.rank() == 0:
        print(f"pipeline stages={S} microbatches={M} "
              f"loss {losses[0]:.4f} -> {losses[-1]:.4f}")
    return list(losses)


if __name__ == "__main__":
    main()
